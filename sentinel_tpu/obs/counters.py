"""Decision counters — the runtime's own "why did that happen" tallies.

One flat monotonically-increasing integer per named decision outcome,
mutated per BATCH (not per event) on the hot path so the instrumented
dispatch stays within the 2% observability budget (benchmarks/ci_gate.py
``obs_overhead`` gate). Families:

* ``split_route.*`` — which dispatch path a batch took
  (:meth:`~sentinel_tpu.runtime.Sentinel.decide_raw_nowait` path
  selection): ``scalar`` / ``fast`` / ``fast_occupy`` /
  ``general_sorted``, plus ``split_fired`` when a mixed batch was
  per-event split (``_decide_split_nowait``), ``meshed`` when the
  dispatch ran on a row-sharded engine (alongside its route counter:
  meshed_total/route_total attributes how much traffic the mesh path
  carries), ``sortfree`` when the dispatch's flow programs grouped
  segments sort-free (alongside its route counter, same pattern), and
  ``single_dispatch`` when a whole-batch decide/fused program carried
  the tiering sketch observe inside itself (round 16 — the batch cost
  ONE device dispatch instead of decide + observe).
* ``sortfree.bucket_overflow`` — claim-cascade overflow total: elements
  whose step fell back to the sorted branch (ops/sortfree.py); sustained
  growth means the bucket table is undersized for the key distribution.
* ``compile_cache.*`` — first-dispatch program accounting per (variant,
  geometry, statics) combo: ``hit`` / ``miss`` /
  ``first_fetch_retry`` (the guarded-fetch stall retries).
* ``occupy.*`` — priority booking lifecycle: ``granted`` (PriorityWait
  admissions), ``carried`` / ``settled`` (bookings surviving /
  landing at rule reload), ``evicted`` (cleared by row eviction).
* ``pipeline.*`` — dispatch-pipeline health (sentinel_tpu/serving.py):
  ``depth`` (sum of in-flight handles observed at each enqueue — divide
  by enqueue count for the achieved average depth), ``stall`` (submits
  that had to settle the oldest in-flight batch first),
  ``leaked_handles`` (PendingVerdicts settled by the GC finalizer
  because ``.result()`` was never called), ``meshed_dispatch``
  (submits whose backing Sentinel is row-sharded over a mesh), and
  ``dispatches`` (device dispatches issued by the serving hot path and
  its tickers — dispatches/batch is the round-16 single-dispatch
  headline, gated at 1.0 by benchmarks/ci_gate.py gate (m)).
* ``frontend.*`` — the ingest tier (sentinel_tpu/frontend/):
  ``enqueue`` (requests accepted), ``queue_depth`` (sum of pending
  queue length sampled at each enqueue — divide by enqueues for the
  achieved average depth), ``shed`` (requests rejected at the
  ``queue_max`` backpressure bound), and ``flush_reason.{full,
  deadline, idle}`` (why each device batch was cut).
* ``block_reason.<ExceptionName>`` — per-reason denial breakdown keyed
  by the int8 verdict codes (``exception_name_for`` /
  ``slot_name_for_code`` for custom slots).
* ``obs.span_ring_wrap`` — spans/links lost to per-thread ring wrap
  (capacity 2048 too small for the sustained span rate; previously a
  silent overwrite).
* ``flight.*`` — the SLO flight recorder (obs/flight.py): ``pinned``
  (chains persisted to the ``<app>-trace`` log) and
  ``trigger.{deadline_miss, shed, p99, block_burst}`` (which SLO
  trigger fired, after per-kind rate limiting).
* ``tune.*`` — the serving autotuner (sentinel_tpu/tune/):
  ``config_loaded`` / ``fingerprint_fallback`` (startup resolution of
  the ``SENTINEL_TUNED_CONFIG`` artifact), ``knob_rejected`` (unknown
  or out-of-clamp ``SENTINEL_*`` env keys found at construction),
  ``trial`` (sweep episodes scored against this engine's obs) and
  ``parity_fail`` (verdict bit-parity spot-check failures).
* ``telemetry.*`` — the device-resident hot-resource telemetry layer
  (obs/telemetry.py): ``tick`` (device reads dispatched) and
  ``readback_drop`` (ticks dropped because async host readback fell
  behind — the drop-and-count policy that keeps telemetry off the
  dispatch path).
* ``exporter.label_overflow`` — Prometheus label-cardinality guard
  (metrics/exporter.py): resource-labeled samples dropped at the
  per-family label cap.

:data:`CATALOG` is the fixed, ordered multihost-aggregatable key set:
every process packs its snapshot into one int64 vector
(:func:`catalog_vector`) for a single ``process_allgather``
(multihost/obs_agg.py) — dynamic keys (custom-slot block reasons)
aggregate only through the transport surface.
"""

from __future__ import annotations

import threading
from typing import Dict, Mapping

ROUTE_SCALAR = "split_route.scalar"
ROUTE_FAST = "split_route.fast"
ROUTE_FAST_OCCUPY = "split_route.fast_occupy"
ROUTE_GENERAL = "split_route.general_sorted"
ROUTE_SPLIT = "split_route.split_fired"

CACHE_HIT = "compile_cache.hit"
CACHE_MISS = "compile_cache.miss"
CACHE_RETRY = "compile_cache.first_fetch_retry"

OCCUPY_GRANTED = "occupy.granted"
OCCUPY_CARRIED = "occupy.carried"
OCCUPY_SETTLED = "occupy.settled"
OCCUPY_EVICTED = "occupy.evicted"

ROUTE_FUSED = "split_route.fused_exit"

PIPE_DEPTH = "pipeline.depth"
PIPE_STALL = "pipeline.stall"
PIPE_LEAKED = "pipeline.leaked_handles"

FE_ENQUEUE = "frontend.enqueue"
FE_QUEUE_DEPTH = "frontend.queue_depth"
FE_SHED = "frontend.shed"
FE_FLUSH_FULL = "frontend.flush_reason.full"
FE_FLUSH_DEADLINE = "frontend.flush_reason.deadline"
FE_FLUSH_IDLE = "frontend.flush_reason.idle"

BLOCK_PREFIX = "block_reason."

# PR 8 — tracing / flight-recorder health
SPAN_RING_WRAP = "obs.span_ring_wrap"     # spans/links lost to ring wrap
FLIGHT_PINNED = "flight.pinned"           # chains pinned by an SLO trigger
FLIGHT_TRIGGER_PREFIX = "flight.trigger."  # per-kind trigger tallies

# PR 9 — meshed serving hot path: dispatches decided by a row-sharded
# engine (one per decide/split/fused dispatch alongside its route
# counter) and pipeline submits whose backing Sentinel is meshed
ROUTE_MESHED = "split_route.meshed"
PIPE_MESHED = "pipeline.meshed_dispatch"

# PR 10 — sort-free general path: dispatches whose flow programs grouped
# segments via the hash-bucketed claim cascade (one per decide/split/
# fused dispatch alongside its route counter, like ROUTE_MESHED), and
# the per-step claim-cascade overflow tally (elements that took the
# sorted fallback branch under lax.cond — sustained growth means the
# bucket table is undersized for the live key distribution; see
# docs/OPERATIONS.md "Sort-free general path")
ROUTE_SORTFREE = "split_route.sortfree"
SORTFREE_OVERFLOW = "sortfree.bucket_overflow"

# PR 11 — serving autotuner (sentinel_tpu/tune/): startup resolution of
# the SENTINEL_TUNED_CONFIG artifact (loaded vs fingerprint-mismatch
# fallback to defaults), the knob-registry validation warnings (unknown
# or out-of-clamp SENTINEL_* env keys — one tick per finding at Sentinel
# construction), and sweep health (trials run on this engine's obs,
# verdict bit-parity spot-check failures — any nonzero parity_fail
# disqualifies the sweep)
TUNE_LOADED = "tune.config_loaded"
TUNE_FALLBACK = "tune.fingerprint_fallback"
TUNE_KNOB_REJECTED = "tune.knob_rejected"
TUNE_TRIAL = "tune.trial"
TUNE_PARITY_FAIL = "tune.parity_fail"

# PR 12 — device-resident hot-resource telemetry (obs/telemetry.py):
# ``tick`` counts telemetry reads dispatched over the live window state,
# ``readback_drop`` counts ticks skipped because the asynchronous host
# readback fell PENDING_MAX behind (drop-and-count: the dispatch path is
# never blocked on a telemetry sync — sustained growth means the
# telemetry thread is starved). ``label_overflow`` is the exporter's
# label-cardinality guard (metrics/exporter.py): per-resource label
# values beyond the cap are dropped from the scrape and counted here.
TELEMETRY_TICK = "telemetry.tick"
TELEMETRY_DROP = "telemetry.readback_drop"
EXPORTER_LABEL_OVERFLOW = "exporter.label_overflow"

# PR 15 — tiered resource state (sentinel_tpu/tiering/): ``hot_hit`` /
# ``cold_miss`` classify interns of keys the tier system already knows
# (resident row vs cold-tier restore — brand-new keys tick NEITHER, so
# the hit rate measures hot-tier sizing rather than keyspace size);
# ``promoted`` / ``demoted`` count row migrations between the device hot
# tier and the host cold tier (``demoted`` ticks on the invalidation
# drain as each recycled row's state is snapshotted out; with tiering
# disabled the drain is the pre-round-15 lossy invalidate and only
# ``occupy.evicted`` ticks);
# ``sketch_overflow`` counts count-min table halvings (estimates are
# relative, halving preserves the hot/cold ranking — sustained growth
# just means a long-lived process, not a fault). Exported as
# ``sentinel_tier_total{event=...}``; see docs/OPERATIONS.md
# "Tiered resource state (round 15)".
TIER_HOT_HIT = "tier.hot_hit"
TIER_COLD_MISS = "tier.cold_miss"
TIER_PROMOTED = "tier.promoted"
TIER_DEMOTED = "tier.demoted"
TIER_SKETCH_OVERFLOW = "tier.sketch_overflow"

# PR 16 — single-dispatch serving tick: ``pipeline.dispatches`` counts
# DEVICE DISPATCHES issued by the serving hot path and its tickers
# (decide = 1, split = 2, fused decide+exit = 1, exit = 1, a standalone
# sketch observe = 1, a self-dispatched telemetry or tiering tick = 1;
# cold-path programs — invalidation drains, promotions/restores, rule
# reloads — are deliberately NOT counted: the key exists so
# dispatches-per-batch is measurable from obs plumbing alone, and the
# cold path is not per-batch). ``split_route.single_dispatch`` ticks
# once per whole-batch dispatch that carried the tiering sketch update
# inside the decide/fused program itself (the round-16 fused observe —
# alongside its route counter, like ROUTE_MESHED/ROUTE_SORTFREE); the
# per-sub-batch split pipeline fuses the sketch too but keeps its two
# dispatches, so it never ticks this key. Gate (m) in
# benchmarks/ci_gate.py holds steady-state dispatches/batch == 1.
PIPE_DISPATCH = "pipeline.dispatches"
ROUTE_SINGLE_DISPATCH = "split_route.single_dispatch"

# PR 17 — closed-loop overload controller (sentinel_tpu/control/):
# ``tick`` counts policy evaluations (one per ControlLoop cadence slot
# with a fresh observation); the three ``action.*`` keys count APPLIED
# interventions by type (shed-fraction change, batcher retune, forced
# degrade transition — every one is also pinned in the flight recorder
# with its triggering evidence, trigger kind ``controller_action``);
# ``admission_dropped`` counts requests the frontend refused under a
# controller-set admission fraction < 1 (deterministic seeded-hash
# shed, BEFORE batches form — distinct from ``frontend.shed``, the
# queue-overflow backpressure). Exported as
# ``sentinel_control_total{action=...}``; see docs/OPERATIONS.md
# "Self-driving overload protection (round 17)".
CONTROL_TICK = "control.tick"
CONTROL_SHED_ACTION = "control.action.shed_rate"
CONTROL_RETUNE_ACTION = "control.action.retune_batcher"
CONTROL_DEGRADE_ACTION = "control.action.degrade"
CONTROL_DROPPED = "control.admission_dropped"

# PR 20 — device-resident per-resource RT histograms
# (sentinel_tpu/obs/resource_hist.py): ``telemetry.hist_tick`` counts
# telemetry landings that carried per-resource histogram vectors and
# quantiles (0 while ``SENTINEL_RESOURCE_HIST_DISABLE`` drops the
# table — the delta against ``telemetry.tick`` shows the feature
# switch state from the scrape alone); ``control.tail_signal`` counts
# controller ticks whose degrade evaluation ran on per-resource
# interval p99 deltas rather than the pre-r20 hot-set mean RT
# fallback. Exported under the existing ``sentinel_telemetry_total``
# / ``sentinel_control_total`` families; see docs/OBSERVABILITY.md
# "Per-resource RT histograms (round 20)".
TELEMETRY_HIST_TICK = "telemetry.hist_tick"
CONTROL_TAIL_SIGNAL = "control.tail_signal"

#: Fixed aggregation catalog (order is the wire format of the multihost
#: counter vector — append only, never reorder).
CATALOG = (
    ROUTE_SCALAR, ROUTE_FAST, ROUTE_FAST_OCCUPY, ROUTE_GENERAL, ROUTE_SPLIT,
    CACHE_HIT, CACHE_MISS, CACHE_RETRY,
    OCCUPY_GRANTED, OCCUPY_CARRIED, OCCUPY_SETTLED, OCCUPY_EVICTED,
    BLOCK_PREFIX + "FlowException",
    BLOCK_PREFIX + "DegradeException",
    BLOCK_PREFIX + "SystemBlockException",
    BLOCK_PREFIX + "AuthorityException",
    BLOCK_PREFIX + "ParamFlowException",
    ROUTE_FUSED,
    PIPE_DEPTH, PIPE_STALL, PIPE_LEAKED,
    FE_ENQUEUE, FE_QUEUE_DEPTH, FE_SHED,
    FE_FLUSH_FULL, FE_FLUSH_DEADLINE, FE_FLUSH_IDLE,
    SPAN_RING_WRAP, FLIGHT_PINNED,
    FLIGHT_TRIGGER_PREFIX + "deadline_miss",
    FLIGHT_TRIGGER_PREFIX + "shed",
    FLIGHT_TRIGGER_PREFIX + "p99",
    FLIGHT_TRIGGER_PREFIX + "block_burst",
    ROUTE_MESHED, PIPE_MESHED,
    ROUTE_SORTFREE, SORTFREE_OVERFLOW,
    TUNE_LOADED, TUNE_FALLBACK, TUNE_KNOB_REJECTED,
    TUNE_TRIAL, TUNE_PARITY_FAIL,
    TELEMETRY_TICK, TELEMETRY_DROP, EXPORTER_LABEL_OVERFLOW,
    TIER_HOT_HIT, TIER_COLD_MISS, TIER_PROMOTED, TIER_DEMOTED,
    TIER_SKETCH_OVERFLOW,
    PIPE_DISPATCH, ROUTE_SINGLE_DISPATCH,
    CONTROL_TICK, CONTROL_SHED_ACTION, CONTROL_RETUNE_ACTION,
    CONTROL_DEGRADE_ACTION, CONTROL_DROPPED,
    TELEMETRY_HIST_TICK, CONTROL_TAIL_SIGNAL,
)


class CounterSet:
    """Locked flat dict of monotonic counters.

    One uncontended ``lock + dict.get + add`` per increment; increments
    happen once per batch on the dispatch path, so the cost is amortized
    over thousands of events."""

    __slots__ = ("_lock", "_c")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {}

    def add(self, key: str, n: int = 1) -> None:
        if n == 0:
            return
        with self._lock:
            self._c[key] = self._c.get(key, 0) + int(n)

    def get(self, key: str) -> int:
        with self._lock:
            return self._c.get(key, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)

    def merge(self, counts: Mapping[str, int]) -> None:
        """Fold another snapshot in (multihost coordinator aggregation)."""
        with self._lock:
            for k, v in counts.items():
                self._c[k] = self._c.get(k, 0) + int(v)

    def clear(self) -> None:
        with self._lock:
            self._c.clear()


def catalog_vector(counts: Mapping[str, int]):
    """Snapshot → int64 vector over :data:`CATALOG` (allgather payload)."""
    import numpy as np
    return np.asarray([int(counts.get(k, 0)) for k in CATALOG], np.int64)


def vector_counts(vec) -> Dict[str, int]:
    """Inverse of :func:`catalog_vector` (tolerates longer vectors from a
    newer peer — extra trailing entries are unknown keys and dropped)."""
    return {k: int(vec[i]) for i, k in enumerate(CATALOG) if i < len(vec)}
