"""Fixed log-bucketed latency histograms (Monarch-style in-memory
bucketed distributions: a bounded array of power-of-two buckets, cheap to
record, mergeable across processes by plain vector addition).

Bucket ``i`` covers ``(BASE_NS * 2**(i-1), BASE_NS * 2**i]`` nanoseconds
(bucket 0 is ``[0, BASE_NS]``); with ``BASE_NS = 1024`` and 40 buckets the
range runs ~1 µs → ~156 h, far past any latency the runtime can produce.
Percentiles interpolate linearly inside the landing bucket, which makes
them deterministic functions of the recorded values — pinned under the
manual clock in tests/test_obs.py.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

BASE_NS = 1024
NUM_BUCKETS = 40


def bucket_index(v_ns: int) -> int:
    """Bucket for a nanosecond value (clamped into the fixed range)."""
    v = int(v_ns)
    if v <= BASE_NS:
        return 0
    # (1024, 2048] → 1, (2048, 4096] → 2, ...  (bit_length(1024)=11)
    return min(NUM_BUCKETS - 1, (v - 1).bit_length() - 10)


def bucket_bounds_ns() -> List[int]:
    """Upper bound of each bucket, ns (the exporter/doc bucket schema)."""
    return [BASE_NS << i for i in range(NUM_BUCKETS)]


class LogHistogram:
    """Mergeable fixed-geometry histogram; thread-safe, ~O(1) record."""

    __slots__ = ("_lock", "_counts", "_total", "_sum_ns", "_max_ns")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts = [0] * NUM_BUCKETS
        self._total = 0
        self._sum_ns = 0
        self._max_ns = 0

    def record(self, v_ns: int) -> None:
        v = max(0, int(v_ns))
        i = bucket_index(v)
        with self._lock:
            self._counts[i] += 1
            self._total += 1
            self._sum_ns += v
            if v > self._max_ns:
                self._max_ns = v

    def merge(self, other: "LogHistogram") -> None:
        counts, total, sum_ns, max_ns = other._state()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._total += total
            self._sum_ns += sum_ns
            self._max_ns = max(self._max_ns, max_ns)

    def merge_counts(self, counts, sum_ns: int = 0, max_ns: int = 0) -> None:
        """Fold a raw bucket vector in (multihost aggregation payload)."""
        with self._lock:
            for i, c in enumerate(counts):
                if i < NUM_BUCKETS:
                    self._counts[i] += int(c)
                    self._total += int(c)
            self._sum_ns += int(sum_ns)
            self._max_ns = max(self._max_ns, int(max_ns))

    def _state(self):
        with self._lock:
            return list(self._counts), self._total, self._sum_ns, self._max_ns

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def percentile(self, p: float) -> Optional[float]:
        """p ∈ (0, 1] → interpolated value in ns; None when empty."""
        counts, total, _s, max_ns = self._state()
        if total == 0:
            return None
        rank = max(1.0, p * total)       # 1-based rank of the target sample
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = 0 if i == 0 else (BASE_NS << (i - 1))
                hi = BASE_NS << i
                hi = min(hi, max_ns) if i == NUM_BUCKETS - 1 else hi
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return float(max_ns)             # pragma: no cover - rank rounding

    def percentile_ms(self, p: float) -> Optional[float]:
        v = self.percentile(p)
        return None if v is None else v / 1e6

    def snapshot(self) -> Dict:
        counts, total, sum_ns, max_ns = self._state()
        out: Dict = {"count": total, "sum_ns": sum_ns, "max_ns": max_ns,
                     "buckets": counts}
        for name, p in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            v = self.percentile(p)
            out[f"{name}_ms"] = None if v is None else v / 1e6
        return out

    def clear(self) -> None:
        with self._lock:
            self._counts = [0] * NUM_BUCKETS
            self._total = 0
            self._sum_ns = 0
            self._max_ns = 0
