"""Device-resident hot-resource telemetry: sharded top-K + per-second
timeline over the live window state (docs/OBSERVABILITY.md).

The reference Sentinel's flagship observability surface is per-resource
second-level metrics — every dashboard curve is built from a host-side
sweep over all StatisticNodes. At this repo's scale (1M resource rows
sharded across a mesh) that sweep is impossible; instead ONE jitted
telemetry tick runs over the live sharded ``WindowState`` without
touching the serving path:

* **(a) sharded top-K** — rolling pass+block load per row
  (:func:`sentinel_tpu.stats.window.rolling_load`, valid-mask-aware over
  the second window), the global ENTRY row masked out, then per-shard
  ``lax.top_k`` merged device-side across the mesh under the
  ``parallel/local_shard.py`` layout authority
  (:func:`~sentinel_tpu.parallel.local_shard.topk_layout`). The merge is
  EXACT, not approximate: row shards are disjoint, so every global
  winner is some shard's local winner; ``lax.top_k`` breaks ties by
  lowest index, and the gathered candidates preserve globally-increasing
  row order among equal loads, so the merged result is bit-identical to
  a host ``argsort(-load, kind="stable")`` (pinned by
  tests/test_telemetry.py on an 8-virtual-device mesh).
* **(b) per-second timeline** — the ENTRY row's completed-second bucket
  (pass/block/rt-sum/occupy lanes) appended into a small device ring
  buffer (:class:`TelemetryRing`) once per wall second.
* **(c) asynchronous host readback** — the tick only *dispatches* under
  the engine lock (fresh output buffers, donation-safe — the
  ``_jit_copy_column`` discipline); ``np.asarray`` happens later on the
  telemetry thread, overlapped with the ``DispatchPipeline``. There is
  never a blocking device sync on a dispatch path. When readback falls
  behind, new ticks are dropped and counted
  (``telemetry.readback_drop``), bounded by :data:`PENDING_MAX`.

Host surfaces: per-resource second lines for the top-K only, riding the
``metrics/writer.py`` rotation as ``<app>-metric`` (read back by
``metrics/searcher.py``); the ``topk`` transport command; the dashboard
``/obs/topk.json`` + hot-resources panel; a bounded-cardinality
Prometheus family (``sentinel_resource_qps`` — top-K labels only); and
the flight recorder's pinned hot-set snapshots (obs/flight.py
``hot_provider``).

Env knobs (construction time; kwargs override):
``SENTINEL_TELEMETRY_K`` — hot-set size, default 16, clamped to
[1, :data:`MAX_K`] and to the row count; ``SENTINEL_TELEMETRY_DISABLE``
— turn the telemetry layer off entirely (the obs master switch
``SENTINEL_OBS_DISABLE`` also turns it off).
"""

from __future__ import annotations

import collections
import functools
import os
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from sentinel_tpu.core.registry import ENTRY_NODE_ROW
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.obs import resource_hist
from sentinel_tpu.stats import events as ev
from sentinel_tpu.stats import window
from sentinel_tpu.parallel.local_shard import MESH_AXIS, topk_layout

try:  # jax >= 0.6 exposes shard_map at top level (kwarg: check_vma)
    from jax import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover — older jax (kwarg: check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_rep"


def _shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: ``check_vma`` (≥ 0.6) and its
    predecessor ``check_rep`` are the same switch under different names."""
    return _shard_map_impl(body, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs,
                           **{_SM_CHECK_KW: check_vma})


TELEMETRY_K_ENV = "SENTINEL_TELEMETRY_K"
TELEMETRY_DISABLE_ENV = "SENTINEL_TELEMETRY_DISABLE"

DEFAULT_K = 16
MAX_K = 128
RING_SLOTS = 64          # device timeline ring depth (~1 min at 1 Hz)
PENDING_MAX = 2          # un-drained device readbacks before drop-and-count
HOT_TIMELINE_CAP = 120   # host-side timeline tail kept for the command/SPA
FLIGHT_HOT_N = 8         # hot entries pinned into flight trigger records


def telemetry_disabled() -> bool:
    return os.environ.get(TELEMETRY_DISABLE_ENV, "").lower() in (
        "1", "true", "on", "yes")


def telemetry_k(default: int = DEFAULT_K) -> int:
    raw = os.environ.get(TELEMETRY_K_ENV, "")
    if not raw:
        return default
    try:
        return max(1, min(MAX_K, int(raw)))
    except ValueError:
        return default


class TelemetryRing(NamedTuple):
    """Device-resident per-second timeline ring (replicated — it is a few
    KB; only the write index moves)."""

    seconds: jnp.ndarray   # int32[S] minute-window idx written (NEVER=empty)
    lanes: jnp.ndarray     # int32[S, E] ENTRY-row completed-second lanes
    rt: jnp.ndarray        # float32[S] ENTRY-row completed-second rt sum
    cursor: jnp.ndarray    # int32[] total appends (slot = cursor % S)


def init_ring(slots: int = RING_SLOTS,
              num_events: int = ev.NUM_EVENTS) -> TelemetryRing:
    return TelemetryRing(
        seconds=jnp.full((slots,), window.NEVER, jnp.int32),
        lanes=jnp.zeros((slots, num_events), jnp.int32),
        rt=jnp.zeros((slots,), jnp.float32),
        cursor=jnp.zeros((), jnp.int32),
    )


def _sharded_topk(load: jnp.ndarray, k: int, mesh,
                  rows_per_shard: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact device-side top-K merge over disjoint row shards.

    Each shard ranks its own rows (``k_local = min(k, rows_per_shard)``
    candidates are enough: at most k global winners live in one shard),
    candidates gather across the mesh, and one final ``top_k`` ranks the
    ``n_shards × k_local`` survivors — O(n·k) gathered instead of the
    full row axis. Tie-break equals the host stable argsort: within a
    shard ``top_k`` prefers the lowest row, the gather concatenates
    shards in row order, so equal-load candidates stay in ascending
    global-row order and the final ``top_k`` keeps the lowest rows.
    """
    k_local = min(k, rows_per_shard)

    def body(l):
        vals, idx = lax.top_k(l, k_local)
        rows = idx.astype(jnp.int32) + lax.axis_index(MESH_AXIS) * rows_per_shard
        vals = lax.all_gather(vals, MESH_AXIS)   # [n, k_local]
        rows = lax.all_gather(rows, MESH_AXIS)
        mv, mi = lax.top_k(vals.reshape(-1), k)
        return mv, rows.reshape(-1)[mi]

    return _shard_map(body, mesh=mesh, in_specs=P(MESH_AXIS),
                      out_specs=(P(), P()), check_vma=False)(load)


def telemetry_tick(second_spec: window.WindowSpec,
                   minute_spec: Optional[window.WindowSpec],
                   k: int, mesh, rows_per_shard: int,
                   second: window.WindowState,
                   minute: window.WindowState,
                   rt_hist,
                   ring: TelemetryRing,
                   now_idx_s: jnp.ndarray, sec_idx_m: jnp.ndarray,
                   append: jnp.ndarray):
    """ONE fused telemetry read over the live state (pure; jitted by
    :class:`HotTelemetry`). Returns fresh output buffers only — safe to
    read back asynchronously while later steps donate the state.

    ``rt_hist`` is the round-20 per-resource cumulative RT histogram
    table (``SentinelState.rt_hist``; None when the engine has no
    table). When present, the hot set's histogram rows gather alongside
    the rolling lanes (disjoint row shards — same GSPMD pattern as
    ``rolling_totals``) and the jitted quantile extraction
    (:func:`sentinel_tpu.obs.resource_hist.quantiles_from_counts`)
    rides the same dispatch; when None both extra outputs are
    zero-width, keeping every downstream tuple shape static."""
    rows_total = second.stamps.shape[0]
    load = window.rolling_load(second_spec, second, now_idx_s)
    # the global ENTRY aggregate row receives every inbound event — it is
    # the timeline source, never a "hot resource"
    load = jnp.where(
        jnp.arange(rows_total, dtype=jnp.int32) == ENTRY_NODE_ROW,
        jnp.int32(-1), load)
    if mesh is not None and mesh.shape[MESH_AXIS] > 1:
        vals, rows = _sharded_topk(load, k, mesh, rows_per_shard)
    else:
        vals, rows = lax.top_k(load, k)
        rows = rows.astype(jnp.int32)
    roll_lanes = window.rolling_totals(second_spec, second, now_idx_s)[rows]
    if minute_spec is not None:
        mc, mrt = window.bucket_snapshot(minute_spec, minute, sec_idx_m)
        sec_lanes, sec_rt = mc[rows], mrt[rows]
        entry_lanes, entry_rt = mc[ENTRY_NODE_ROW], mrt[ENTRY_NODE_ROW]
    else:   # minute ring disabled: hot set only, no per-second surfaces
        sec_lanes = jnp.zeros_like(roll_lanes)
        sec_rt = jnp.zeros((k,), jnp.float32)
        entry_lanes = jnp.zeros((ring.lanes.shape[1],), jnp.int32)
        entry_rt = jnp.zeros((), jnp.float32)
    if rt_hist is not None:
        hist_k = rt_hist[rows]                       # [k, HB] cumulative
        q_k = resource_hist.quantiles_from_counts(hist_k)   # [k, 3] ms
    else:
        hist_k = jnp.zeros((k, 0), jnp.int32)
        q_k = jnp.zeros((k, 0), jnp.float32)
    slots = ring.seconds.shape[0]
    slot = ring.cursor % slots
    keep = append > 0
    ring = TelemetryRing(
        seconds=ring.seconds.at[slot].set(
            jnp.where(keep, sec_idx_m, ring.seconds[slot])),
        lanes=ring.lanes.at[slot].set(
            jnp.where(keep, entry_lanes, ring.lanes[slot])),
        rt=ring.rt.at[slot].set(jnp.where(keep, entry_rt, ring.rt[slot])),
        cursor=ring.cursor + keep.astype(jnp.int32),
    )
    return (vals, rows, roll_lanes, sec_lanes, sec_rt,
            entry_lanes, entry_rt, hist_k, q_k), ring


class HotTelemetry:
    """The per-``Sentinel`` hot-resource telemetry service
    (``Sentinel.telemetry``).

    Host-side contract: :meth:`tick` dispatches the device read under the
    engine lock (no sync); :meth:`drain` resolves queued readbacks OFF the
    lock; :meth:`poll` is the ticker-thread body. All reads
    (:meth:`snapshot`, :meth:`hot_entries`) serve from the last drained
    host view under a telemetry-local lock — never from device state.
    """

    def __init__(self, sentinel, *, k: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 ring_slots: int = RING_SLOTS) -> None:
        self._sentinel = sentinel
        self._obs = sentinel.obs
        if enabled is None:
            enabled = sentinel.obs.enabled and not telemetry_disabled()
        self.enabled = enabled
        spec = sentinel.spec
        self.k = max(1, min(k if k is not None else telemetry_k(),
                            MAX_K, spec.rows))
        self.ring_slots = int(ring_slots)
        self._n_shards, self._rows_per_shard = topk_layout(
            spec, sentinel.mesh)
        self._lock = threading.Lock()          # telemetry-local host state
        self._pending: "collections.deque" = collections.deque()
        self._drops = 0
        self._ticks = 0
        self._ring: Optional[TelemetryRing] = None
        self._tick_fn = None
        self._hot: List[Dict] = []
        self._timeline: "collections.deque" = collections.deque(
            maxlen=HOT_TIMELINE_CAP)
        self._last_raw: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._last_ts_ms = 0
        # the first completed second is the one the clock is currently in
        # minus one; earlier seconds pre-date this service
        self._last_sec = sentinel.clock.now_ms() // 1000 - 1
        # round 16 — epilogue carry cadence: when armed (CadenceScheduler,
        # serving.py), serving traffic runs the telemetry tick inside the
        # fused dispatch and the ticker only self-dispatches on idle gaps
        self._carry_ms: Optional[int] = None
        self._last_tick_ms = int(sentinel.clock.now_ms())
        self.writer = None
        self.base_name: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)
        if self.enabled:
            # flight triggers pin the hot set as seen at trigger time
            sentinel.obs.flight.hot_provider = self.flight_hot

    # ---- persistence wiring (bootstrap / tests) ----------------------

    def configure(self, base_dir: str, app_name: str, *,
                  single_file_size: int = 50 * 1024 * 1024,
                  total_file_count: int = 6) -> str:
        """Attach the rolling ``<app>-metric`` writer (idempotent per
        instance); → the on-disk base name the searcher should use."""
        from sentinel_tpu.metrics.writer import MetricWriter, \
            form_metric_file_name
        if self.writer is None:
            self.writer = MetricWriter(
                base_dir, app_name + "-metric",
                single_file_size=single_file_size,
                total_file_count=total_file_count)
            self.base_name = form_metric_file_name(app_name + "-metric")
        return self.base_name

    # ---- device side -------------------------------------------------

    def _build_tick(self):
        spec = self._sentinel.spec
        return jax.jit(functools.partial(
            telemetry_tick, spec.second, spec.minute, self.k,
            self._sentinel.mesh, self._rows_per_shard))

    def tick(self) -> bool:
        """Dispatch one telemetry read; → True when a readback was
        queued (False: disabled, closed, or dropped because the drain
        side is :data:`PENDING_MAX` behind)."""
        if not self.enabled or self._closed:
            return False
        with self._lock:
            if len(self._pending) >= PENDING_MAX:
                self._drops += 1
                drop = True
            else:
                drop = False
        if drop:
            self._obs.counters.add(obs_keys.TELEMETRY_DROP)
            return False
        sn = self._sentinel
        now_ms = sn.clock.now_ms()
        sec = now_ms // 1000 - 1               # last COMPLETED second
        append = 1 if sec > self._last_sec else 0
        spec = sn.spec
        idx_s = jnp.int32(spec.second.index_of(now_ms))
        sec_idx_m = jnp.int32(spec.minute.index_of(sec * 1000)
                              if spec.minute is not None else 0)
        with sn._lock:
            if self._tick_fn is None:
                self._tick_fn = self._build_tick()
            if self._ring is None:
                self._ring = init_ring(self.ring_slots)
            outs, self._ring = self._tick_fn(
                sn._state.second, sn._state.minute, sn._state.rt_hist,
                self._ring, idx_s, sec_idx_m, np.int32(append))
        if append:
            self._last_sec = sec
        with self._lock:
            self._pending.append((now_ms, sec, append, outs))
            self._ticks += 1
            self._last_tick_ms = int(now_ms)
        self._obs.counters.add(obs_keys.TELEMETRY_TICK)
        if self._obs.enabled:
            self._obs.counters.add(obs_keys.PIPE_DISPATCH)
        return True

    # ---- round 16: single-dispatch epilogue surface ------------------

    def arm_carry(self, interval_ms: int) -> None:
        """Let serving traffic carry the telemetry tick inside the fused
        dispatch at this cadence (CadenceScheduler, serving.py)."""
        with self._lock:
            self._carry_ms = max(1, int(interval_ms))
            self._last_tick_ms = int(self._sentinel.clock.now_ms())

    def disarm_carry(self) -> None:
        with self._lock:
            self._carry_ms = None

    def last_tick_ms(self) -> int:
        with self._lock:
            return self._last_tick_ms

    def carry_due_locked(self, now_ms: int):
        """Engine lock held: claim one epilogue-carried tick if the
        cadence is armed and due; → the host scalars the runtime feeds
        the fused program's ``lax.cond`` epilogue
        (``(now_ms, sec, append, now_idx_s, sec_idx_m)``) or None.

        Exactly :meth:`tick`'s host prep — same drop-and-count bound,
        same completed-second bookkeeping — minus the dispatch, which
        the caller's fused serving program performs in the same engine
        lock hold. The claim updates ``_last_tick_ms``/``_last_sec``
        immediately so a concurrent self-dispatch fallback won't
        double-tick."""
        if not self.enabled or self._closed:
            return None
        with self._lock:
            if (self._carry_ms is None
                    or now_ms - self._last_tick_ms < self._carry_ms):
                return None
            # claim the due slot even on drop: re-attempting every batch
            # until the drain catches up would spam readback_drop far
            # beyond the armed cadence
            self._last_tick_ms = int(now_ms)
            if len(self._pending) >= PENDING_MAX:
                self._drops += 1
                drop = True
            else:
                drop = False
        if drop:
            self._obs.counters.add(obs_keys.TELEMETRY_DROP)
            return None
        sec = now_ms // 1000 - 1               # last COMPLETED second
        append = 1 if sec > self._last_sec else 0
        spec = self._sentinel.spec
        idx_s = int(spec.second.index_of(now_ms))
        sec_idx_m = int(spec.minute.index_of(sec * 1000)
                        if spec.minute is not None else 0)
        if append:
            self._last_sec = sec
        return (int(now_ms), int(sec), append, idx_s, sec_idx_m)

    def ring_for_fuse_locked(self) -> TelemetryRing:
        """Engine lock held: the timeline ring operand for a fused
        epilogue dispatch (lazily built, like :meth:`tick`'s)."""
        if self._ring is None:
            self._ring = init_ring(self.ring_slots)
        return self._ring

    def set_ring_locked(self, ring: TelemetryRing) -> None:
        """Engine lock held: store the donated-output ring returned by a
        fused epilogue dispatch whose telemetry branch was SKIPPED (the
        ring operand is donated either way)."""
        self._ring = ring

    def queue_carry(self, prep, outs, ring: TelemetryRing) -> None:
        """Engine lock held: queue the readback of an epilogue-carried
        tick (``prep`` is :meth:`carry_due_locked`'s claim; the host
        copy was started by the runtime). :meth:`drain` lands it exactly
        like a self-dispatched one."""
        now_ms, sec, append, _idx_s, _sec_idx_m = prep
        self._ring = ring
        with self._lock:
            self._pending.append((now_ms, sec, append, outs))
            self._ticks += 1
        self._obs.counters.add(obs_keys.TELEMETRY_TICK)

    # ---- host side ---------------------------------------------------

    def drain(self) -> int:
        """Resolve every queued device readback into the host view (and
        the ``<app>-metric`` log); → entries drained. Runs OFF the engine
        lock: ``np.asarray`` here blocks only the telemetry thread."""
        with self._lock:
            batch = list(self._pending)
            self._pending.clear()
        for now_ms, sec, append, outs in batch:
            self._land(now_ms, sec, append,
                       tuple(np.asarray(o) for o in outs))
        return len(batch)

    def _land(self, now_ms: int, sec: int, append: int, outs) -> None:
        (vals, rows, roll_lanes, sec_lanes, sec_rt,
         entry_lanes, entry_rt, hist_k, q_k) = outs
        has_hist = hist_k.shape[1] > 0
        names = dict((row, name)
                     for name, row in self._sentinel.resources.items())
        rtypes = dict(self._sentinel.resource_types)
        interval_s = self._sentinel.spec.second.interval_ms / 1000.0
        hot: List[Dict] = []
        for i in range(len(vals)):
            load = int(vals[i])
            if load <= 0:
                continue
            row = int(rows[i])
            name = names.get(row)
            if name is None:        # stale row (evicted since the tick)
                continue
            lanes = roll_lanes[i]
            succ_s = int(sec_lanes[i][ev.SUCCESS])
            entry = {
                "resource": name, "row": row, "load": load,
                "qps": round(load / interval_s, 3),
                "pass": int(lanes[ev.PASS]), "block": int(lanes[ev.BLOCK]),
                "success": int(lanes[ev.SUCCESS]),
                "exception": int(lanes[ev.EXCEPTION]),
                # device-measured mean RT over the landed second — the
                # pre-r20 degrade signal, kept as the hist-off fallback
                "rt_ms": round(float(sec_rt[i]) / succ_s, 3) if succ_s
                         else 0.0,
            }
            if has_hist:
                # round 20: lifetime-cumulative tail view (display /
                # Prometheus); the controller differences the raw
                # vector itself for interval tails
                entry["rt_p50_ms"] = round(float(q_k[i][0]), 3)
                entry["rt_p95_ms"] = round(float(q_k[i][1]), 3)
                entry["rt_p99_ms"] = round(float(q_k[i][2]), 3)
                entry["rt_hist"] = hist_k[i].tolist()
            hot.append(entry)
        if has_hist and hot:
            self._obs.counters.add(obs_keys.TELEMETRY_HIST_TICK)
        timeline_entry = None
        nodes = []
        if append and self._sentinel.spec.minute is not None:
            timeline_entry = {
                "sec": int(sec),
                "pass": int(entry_lanes[ev.PASS]),
                "block": int(entry_lanes[ev.BLOCK]),
                "success": int(entry_lanes[ev.SUCCESS]),
                "exception": int(entry_lanes[ev.EXCEPTION]),
                "occupied_pass": int(entry_lanes[ev.OCCUPIED_PASS]),
                "rt_sum": round(float(entry_rt), 3),
            }
            if self.writer is not None:
                from sentinel_tpu.metrics.node import MetricNode
                for i, h in enumerate(hot):
                    c = sec_lanes[i]
                    if not (c[ev.PASS] or c[ev.BLOCK] or c[ev.SUCCESS]
                            or c[ev.EXCEPTION]):
                        continue
                    succ = int(c[ev.SUCCESS])
                    nodes.append(MetricNode(
                        timestamp=sec * 1000, resource=h["resource"],
                        pass_qps=int(c[ev.PASS]),
                        block_qps=int(c[ev.BLOCK]), success_qps=succ,
                        exception_qps=int(c[ev.EXCEPTION]),
                        rt=int(float(sec_rt[i]) / succ) if succ else 0,
                        occupied_pass_qps=int(c[ev.OCCUPIED_PASS]),
                        classification=rtypes.get(h["resource"], 0)))
                nodes.sort(key=lambda n: n.resource)
        with self._lock:
            self._hot = hot
            self._last_raw = (vals, rows)
            self._last_ts_ms = int(now_ms)
            if timeline_entry is not None:
                self._timeline.append(timeline_entry)
        if nodes:   # writer.write serializes internally; seconds ascend
            self.writer.write(sec * 1000, nodes)

    def poll(self) -> int:
        """Ticker-thread body (callable directly in tests): one dispatch
        plus the drain of everything queued so far."""
        self.tick()
        return self.drain()

    # ---- read surface ------------------------------------------------

    def snapshot(self, timeline_limit: int = 60) -> Dict:
        """The ``topk`` transport command / ``/obs/topk.json`` body."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "k": self.k,
                "ts_ms": self._last_ts_ms,
                "n_shards": self._n_shards,
                "rows_per_shard": self._rows_per_shard,
                "hot": list(self._hot),
                "timeline": list(self._timeline)[-timeline_limit:],
                "ticks": self._ticks,
                "drops": self._drops,
            }

    def hot_entries(self, n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            hot = list(self._hot)
        return hot if n is None else hot[:n]

    def flight_hot(self) -> List[Dict]:
        """Compact hot-set view pinned into flight trigger records."""
        return [{"resource": h["resource"], "qps": h["qps"]}
                for h in self.hot_entries(FLIGHT_HOT_N)]

    @property
    def last_topk(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(loads, rows) of the last drained tick, raw and unfiltered —
        the exactness probe the tests compare against a host recompute."""
        with self._lock:
            return self._last_raw

    # ---- lifecycle ---------------------------------------------------

    def start(self, interval_sec: float = 1.0) -> None:
        """Start the telemetry daemon (no-op when disabled/running)."""
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_sec):
                try:
                    self.poll()
                except Exception:  # pragma: no cover — keep daemon alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sentinel-telemetry")
        self._thread.start()

    def stop(self) -> None:
        """Idempotent: join the daemon, drain what is queued, close the
        writer. Registered with ``Sentinel.register_shutdown``."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._closed:
            return
        self._closed = True
        try:
            self.drain()
        except Exception:   # teardown must not depend on device health
            pass
        self.enabled = False
        if self.writer is not None:
            self.writer.close()


__all__ = [
    "TELEMETRY_K_ENV", "TELEMETRY_DISABLE_ENV", "DEFAULT_K", "MAX_K",
    "RING_SLOTS", "PENDING_MAX", "FLIGHT_HOT_N", "TelemetryRing",
    "init_ring", "telemetry_tick", "telemetry_disabled", "telemetry_k",
    "HotTelemetry",
]
