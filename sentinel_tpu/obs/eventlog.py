"""Sampled structured block-event log (reference: Sentinel's block log /
the EagleEye record), riding the metric-file rotation machinery.

Where ``core/logs.BlockStatLogger`` rolls denials up per second for the
pipe-delimited block log, this log keeps individual (sampled) denial
RECORDS in the dashboard-readable metric-line format: each event becomes
one :class:`~sentinel_tpu.metrics.node.MetricNode` fat line written
through a dedicated :class:`~sentinel_tpu.metrics.writer.MetricWriter`
(same size/day rotation + .idx sidecar), under the app name
``<app>-block`` — so ``MetricSearcher(dir, form_metric_file_name(app +
"-block"))`` reads events back by time range and resource
(tests/test_obs.py pins the round trip).

Record encoding (docs/OBSERVABILITY.md):

* ``resource`` — the denied resource; when the event carried an origin it
  is appended as ``resource@origin`` (``@`` survives the writer's ``|``
  sanitization, and origin-less events stay exactly searchable by name);
* ``block_qps`` — how many denials this (sampled) record represents (the
  batch tier groups identical denials before logging);
* ``classification`` — the int8 verdict reason code
  (``BlockReason`` / custom-slot codes, ``slot_name_for_code``);
* everything else 0.

Sampling shares the span recorder's deterministic stride
(``SENTINEL_TRACE_SAMPLE``); until :meth:`configure` attaches a writer,
events buffer in a bounded deque readable via :meth:`snapshot` (the
transport/dashboard "recent denials" view) without touching disk.
"""

from __future__ import annotations

import collections
import itertools
import threading
from typing import Dict, List, Optional

RECENT_CAP = 256          # in-memory tail for the command surface
PENDING_CAP = 4096        # un-flushed disk buffer bound (oldest dropped)


class BlockEventLog:
    def __init__(self, sample: float = 1.0) -> None:
        self._stride = 0 if sample <= 0 else max(1, round(1.0 / sample))
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._pending: List[tuple] = []      # (ms, resource, code, origin, n)
        self._recent: "collections.deque" = collections.deque(
            maxlen=RECENT_CAP)
        self._dropped = 0
        self.writer = None
        self.base_name: Optional[str] = None
        self._closed = False

    def configure(self, base_dir: str, app_name: str, *,
                  single_file_size: int = 50 * 1024 * 1024,
                  total_file_count: int = 6) -> str:
        """Attach the rolling metric writer (idempotent per instance);
        → the on-disk base file name the searcher should use."""
        from sentinel_tpu.metrics.writer import MetricWriter, \
            form_metric_file_name
        if self.writer is None:
            self.writer = MetricWriter(
                base_dir, app_name + "-block",
                single_file_size=single_file_size,
                total_file_count=total_file_count)
            self.base_name = form_metric_file_name(app_name + "-block")
        return self.base_name

    def log(self, ms: int, resource: str, reason_code: int,
            reason_name: str = "", origin: str = "", count: int = 1) -> None:
        if self._closed or self._stride == 0:
            return
        if next(self._seq) % self._stride:
            return
        ev = (int(ms), resource, int(reason_code), origin, int(count))
        with self._lock:
            self._recent.append({"ms": ev[0], "resource": resource,
                                 "reason": int(reason_code),
                                 "reason_name": reason_name,
                                 "origin": origin, "count": int(count)})
            self._pending.append(ev)
            if len(self._pending) > PENDING_CAP:
                self._dropped += len(self._pending) - PENDING_CAP
                del self._pending[:len(self._pending) - PENDING_CAP]

    def flush(self) -> int:
        """Write pending events; → lines written. Events are grouped by
        second and written in ascending order (the writer silently drops
        seconds older than its high-water mark)."""
        if self.writer is None:
            return 0
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return 0
        from sentinel_tpu.metrics.node import MetricNode
        by_sec: Dict[int, List[MetricNode]] = {}
        for ms, resource, code, origin, count in pending:
            name = f"{resource}@{origin}" if origin else resource
            by_sec.setdefault(ms // 1000, []).append(MetricNode(
                timestamp=ms, resource=name, block_qps=count,
                classification=code))
        written = 0
        for sec in sorted(by_sec):
            nodes = by_sec[sec]
            self.writer.write(sec * 1000, nodes)
            written += len(nodes)
        return written

    def snapshot(self, limit: int = 64) -> List[Dict]:
        with self._lock:
            tail = list(self._recent)
        return tail[-limit:]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def close(self) -> None:
        """Idempotent: flush what a writer can take, then stop accepting."""
        if self._closed:
            return
        self._closed = True
        try:
            self.flush()
        finally:
            if self.writer is not None:
                self.writer.close()
