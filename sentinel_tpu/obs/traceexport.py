"""Chrome-trace-event / Perfetto export of causal span chains.

Converts the span recorder's ``causal()`` payload (or a flight-recorder
pinned record — same shape) into the Trace Event Format that
``chrome://tracing`` and https://ui.perfetto.dev load directly
(JSON Object Format, ``{"traceEvents": [...]}``):

* every span becomes one complete duration event (``"ph": "X"``) — ``ts``
  / ``dur`` in MICROseconds (the format's unit) from the recorder's ns,
  ``tid`` the recording thread ident, so the per-thread rings render as
  per-thread tracks;
* every causal link becomes a flow-arrow pair — ``"ph": "s"`` (start)
  anchored inside a span of the source trace and ``"ph": "f"`` with
  ``"bp": "e"`` (bind to enclosing slice) inside a span of the
  destination trace — drawing the cross-thread fan-in (request → flush
  batch) and fan-out (batch → verdict) arrows.

Everything here is pure data transformation over already-snapshot
dicts — no recorder access, no locks — so the transport ``trace``
command, the dashboard ``/obs/traces.json`` proxy, the serving-bench
worst-request dump and the tests all share one code path
(tests/test_tracing.py round-trips the output through ``json.loads``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

CATEGORY = "sentinel"
FLOW_CATEGORY = "sentinel.flow"


def _anchor_src(spans: List[Dict], ts_ns: int) -> Optional[Dict]:
    """The source-trace span a flow arrow starts from: the last span
    starting at or before the link timestamp, else the first span."""
    best = None
    for s in spans:
        if s["start_ns"] <= ts_ns and (
                best is None or s["start_ns"] >= best["start_ns"]):
            best = s
    return best if best is not None else (spans[0] if spans else None)


def _anchor_dst(spans: List[Dict], ts_ns: int) -> Optional[Dict]:
    """The destination-trace span a flow arrow lands in: the first span
    ending at or after the link timestamp, else the last span."""
    best = None
    for s in spans:
        if s["end_ns"] >= ts_ns and (
                best is None or s["start_ns"] <= best["start_ns"]):
            best = s
    return best if best is not None else (spans[-1] if spans else None)


def _clamp(ts_ns: int, span: Dict) -> int:
    return min(max(ts_ns, span["start_ns"]), span["end_ns"])


def chrome_trace_events(spans: List[Dict], links: List[Dict],
                        pid: int = 1) -> List[Dict]:
    """Span/link dicts → a flat trace-event list (durations + flows)."""
    events: List[Dict] = []
    by_trace: Dict[int, List[Dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
        events.append({
            "ph": "X", "name": s["name"], "cat": CATEGORY,
            "ts": s["start_ns"] / 1000.0,
            # zero-duration ManualClock spans still need visible slices
            "dur": max(s["end_ns"] - s["start_ns"], 1) / 1000.0,
            "pid": pid, "tid": s["thread"],
            "args": {"trace": s["trace"], "n": s["n"], "note": s["note"]},
        })
    for i, ln in enumerate(links, start=1):
        src = _anchor_src(by_trace.get(ln["src"], []), ln["ts_ns"])
        dst = _anchor_dst(by_trace.get(ln["dst"], []), ln["ts_ns"])
        if src is None or dst is None:
            continue   # one side of the edge fell off its ring
        name = "link." + ln["kind"]
        events.append({
            "ph": "s", "id": i, "name": name, "cat": FLOW_CATEGORY,
            "ts": _clamp(ln["ts_ns"], src) / 1000.0,
            "pid": pid, "tid": src["thread"],
        })
        events.append({
            "ph": "f", "bp": "e", "id": i, "name": name,
            "cat": FLOW_CATEGORY,
            "ts": _clamp(ln["ts_ns"], dst) / 1000.0,
            "pid": pid, "tid": dst["thread"],
        })
    return events


def chrome_trace(causal: Dict, pid: int = 1) -> Dict:
    """A ``causal()`` payload / flight pinned record → the loadable
    JSON-object-format document."""
    meta = {"root": causal.get("root", 0)}
    for k in ("kind", "note", "ts_ms", "worst_ms", "truncated"):
        if k in causal:
            meta[k] = causal[k]
    return {
        "traceEvents": chrome_trace_events(
            causal.get("spans", []), causal.get("links", []), pid=pid),
        "displayTimeUnit": "ms",
        "otherData": meta,
    }


def export_chain(spans_recorder, trace_id: int, pid: int = 1) -> Dict:
    """Convenience: recorder + root id → loadable trace document."""
    return chrome_trace(spans_recorder.causal(trace_id), pid=pid)


def dumps(doc: Dict) -> str:
    return json.dumps(doc, separators=(",", ":"))
