"""Runtime self-telemetry: spans, decision counters, latency histograms,
block-event log (docs/OBSERVABILITY.md).

The runtime owns one :class:`RuntimeObs` (``Sentinel.obs``) and guards
every instrumentation site with its single ``enabled`` flag, so the hot
path pays one attribute check when observability is off and stays within
2% of the uninstrumented headline when it is on (the ``obs_overhead``
gate in benchmarks/ci_gate.py). Everything here is host-side: no device
work, no background threads — :meth:`RuntimeObs.close` is a pure state
transition and is called by ``Sentinel.close()``.

Env knobs (read at ``RuntimeObs`` construction):

* ``SENTINEL_OBS_DISABLE`` — ``1``/``true`` turns all self-telemetry
  off (spans, counters, histograms, block events);
* ``SENTINEL_TRACE_SAMPLE`` — span/block-event sampling rate in
  ``[0, 1]`` (default 1.0 = every dispatch eligible; rendered as a
  deterministic stride, see obs/spans.py);
* ``SENTINEL_FLIGHT_DISABLE`` / ``SENTINEL_FLIGHT_WINDOW_MS`` /
  ``SENTINEL_FLIGHT_P99_MS`` / ``SENTINEL_FLIGHT_BLOCK_BURST`` — the
  SLO flight recorder (obs/flight.py);
* ``SENTINEL_TELEMETRY_K`` / ``SENTINEL_TELEMETRY_DISABLE`` — the
  device-resident hot-resource telemetry layer (obs/telemetry.py,
  ``Sentinel.telemetry``) — its tick runs on its own thread, not here:
  RuntimeObs itself stays thread-free.

Surfaces: the Prometheus collector (metrics/exporter.py), the ``obs``
transport command (transport/handlers.py), the dashboard
``/obs/telemetry.json`` endpoint + panel, and —  multihost — the
coordinator-side counter aggregation in multihost/obs_agg.py.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional

from sentinel_tpu.obs import counters as counters_mod
from sentinel_tpu.obs.counters import CounterSet
from sentinel_tpu.obs.eventlog import BlockEventLog
from sentinel_tpu.obs.flight import FlightRecorder
from sentinel_tpu.obs.hist import LogHistogram, bucket_bounds_ns
from sentinel_tpu.obs.spans import SpanRecorder

OBS_DISABLE_ENV = "SENTINEL_OBS_DISABLE"
TRACE_SAMPLE_ENV = "SENTINEL_TRACE_SAMPLE"

_NULL_CTX = contextlib.nullcontext()


def obs_disabled() -> bool:
    return os.environ.get(OBS_DISABLE_ENV, "").lower() in (
        "1", "true", "on", "yes")


def trace_sample_rate() -> float:
    raw = os.environ.get(TRACE_SAMPLE_ENV, "")
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` context (names the enclosed
    dispatch in profiler/XProf timelines), or a no-op context when the
    profiler surface is unavailable."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:   # pragma: no cover - profiler-less jax build
        return _NULL_CTX


class RuntimeObs:
    """The per-``Sentinel`` telemetry bundle.

    Attributes the runtime's instrumentation sites touch directly:
    ``enabled`` (the one hot-path guard), ``spans``, ``counters``,
    ``hist_entry`` (entry→verdict ns), ``hist_dispatch``
    (dispatch→verdict-ready device ns), ``hist_request`` (per-REQUEST
    ingest→verdict ns through the serving front end — the end-to-end
    latency a service owner sees; recorded by frontend/batcher.py),
    ``block_events``."""

    def __init__(self, clock=None, enabled: Optional[bool] = None,
                 sample: Optional[float] = None) -> None:
        if sample is None:
            sample = trace_sample_rate()
        self.enabled = (not obs_disabled()) if enabled is None else enabled
        self.sample = sample
        self.clock = clock
        self.counters = CounterSet()
        # ring wrap is an operator signal, not a silent overwrite: each
        # span/link lost to a wrapped per-thread ring ticks the counter
        self.spans = SpanRecorder.for_clock(
            clock, sample=sample,
            on_wrap=lambda: self.counters.add(counters_mod.SPAN_RING_WRAP))
        self.hist_entry = LogHistogram()
        self.hist_dispatch = LogHistogram()
        self.hist_request = LogHistogram()
        self.block_events = BlockEventLog(sample=sample)
        # tail-based SLO capture (obs/flight.py); inert when the bundle
        # is disabled, individually removable via SENTINEL_FLIGHT_DISABLE
        self.flight = FlightRecorder(self)
        self._closed = False

    # ---- hot-path helpers -------------------------------------------

    def request_trace(self) -> int:
        """Trace id for one ingest request/flush: the flight recorder's
        always-on tier mints unconditionally (an SLO trigger must be able
        to pin ANY chain retroactively); otherwise the stride sampler
        decides. → 0 when telemetry is off."""
        if not self.enabled:
            return 0
        if self.flight.active:
            return self.spans.mint()
        return self.spans.maybe_trace()

    def annotate(self, name: str):
        """Profiler trace annotation for a jitted step — a shared no-op
        context when disabled (one truthiness check, no allocation)."""
        if not self.enabled:
            return _NULL_CTX
        return trace_annotation(name)

    # ---- export surface ---------------------------------------------

    def payload(self, span_limit: int = 256,
                event_limit: int = 64) -> Dict:
        """The ``obs`` transport command / dashboard JSON body."""
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "counters": self.counters.snapshot(),
            "hist": {
                "entry_to_verdict": self.hist_entry.snapshot(),
                "dispatch_device": self.hist_dispatch.snapshot(),
                "request_to_verdict": self.hist_request.snapshot(),
                "bucket_bounds_ns": bucket_bounds_ns(),
            },
            "spans": self.spans.snapshot(limit=span_limit),
            "block_events": self.block_events.snapshot(limit=event_limit),
            "flight": {
                "active": self.flight.active,
                "window_ms": self.flight.window_ms,
                "pinned": self.flight.snapshot(),
            },
        }

    def flush(self) -> int:
        """Flush buffered block events + pinned flight chains to their
        writers (ridden by the metric timer's tick and by close)."""
        return self.block_events.flush() + self.flight.flush()

    def close(self) -> None:
        """Idempotent teardown: disable, drop span rings, flush + close
        the block-event and flight-recorder writers. Safe across
        repeated open/close."""
        if self._closed:
            return
        self._closed = True
        self.enabled = False
        self.flight.close()
        self.spans.close()
        self.block_events.close()


__all__ = [
    "OBS_DISABLE_ENV", "TRACE_SAMPLE_ENV", "RuntimeObs", "CounterSet",
    "LogHistogram", "SpanRecorder", "BlockEventLog", "FlightRecorder",
    "obs_disabled", "trace_sample_rate", "trace_annotation", "counters_mod",
]
