"""Cluster-server command-plane handlers (reference
``sentinel-cluster-server-default/.../command/handler/*`` — the 10
``cluster/server/*`` commands the dashboard uses to manage a token server
over HTTP).

Wire formats match the reference: modify/fetch flow rules speak standard
``FlowRule`` JSON (cluster fields in ``clusterConfig`` —
``ModifyClusterFlowRulesCommandHandler.java``), param rules speak
``ParamFlowRule`` JSON, ``fetchConfig`` returns the
``{transport, flow, namespaceSet}`` shape of
``FetchClusterServerConfigHandler.java``, and ``metricList`` returns
``ClusterMetricNode``-shaped dicts.

Register with :func:`register_cluster_server_handlers` — pass a
:class:`~sentinel_tpu.cluster.coordinator.ClusterCoordinator` for live
resolution (the engine/server exist only while serving), or a fixed
engine/server pair for a standalone token-server process.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from sentinel_tpu.transport.command import (
    CommandCenter, CommandRequest, CommandResponse,
)

SUCCESS = "success"


class ClusterServerCommands:
    def __init__(self, *, engine=None, server=None, coordinator=None,
                 clock=None):
        self._engine = engine
        self._server = server
        self.coordinator = coordinator
        self._clock = clock
        # raw rule payloads per namespace: display-field enrichment for
        # fetch (resource names, grades) and verbatim round-trip of
        # non-cluster-mode beans; enforcement fields always come from the
        # engine (see _engine_rule_beans)
        self._raw_flow: Dict[str, List[dict]] = {}
        self._raw_param: Dict[str, List[dict]] = {}
        self._namespace_set: List[str] = []

    # ------------------------------------------------------------- plumbing
    def _resolve_server(self):
        if self._server is not None:
            return self._server
        if self.coordinator is not None:
            return self.coordinator.server
        return None

    def _resolve_engine(self):
        if self._engine is not None:
            return self._engine
        srv = self._resolve_server()
        return srv.engine if srv is not None else None

    def _now_ms(self) -> int:
        if self._clock is not None:
            return self._clock.now_ms()
        if self.coordinator is not None:
            return self.coordinator.clock.now_ms()
        import time
        return int(time.time() * 1000)

    @staticmethod
    def _need(req: CommandRequest, name: str) -> Optional[str]:
        v = req.param(name)
        return v if v else None

    def _engine_or_fail(self):
        eng = self._resolve_engine()
        if eng is None:
            return None, CommandResponse.of_failure(
                "token server not running", 400)
        return eng, None

    # ------------------------------------------------------------ rules
    def _engine_rule_beans(self, ns: str, *, param: bool) -> List[dict]:
        """Rule beans for a namespace derived from ENGINE state (the
        authoritative enforcement tables), enriched with the raw JSON pushed
        through the modify commands when available.  Rules loaded through any
        other path (direct ``engine.load_rules``, a standalone server's own
        config) are synthesized from the engine's rule structs so fetch and
        ``metricList`` never disagree with live enforcement."""
        eng = self._resolve_engine()
        if eng is None:
            return list((self._raw_param if param else
                         self._raw_flow).get(ns, []))
        raw_list = (self._raw_param if param else self._raw_flow).get(ns, [])
        raw = {}
        for d in raw_list:
            fid = (d.get("clusterConfig") or {}).get("flowId")
            if fid is not None and d.get("clusterMode"):
                raw[int(fid)] = d
        beans: List[dict] = []
        for fid, r in eng.namespace_rules(ns, param=param).items():
            if fid in raw:
                # raw bean supplies display fields (resource name, grade…)
                # but ENFORCEMENT fields come from the engine — a direct
                # engine.load_rules after the push must win in fetch too
                bean = dict(raw[fid])
                bean["count"] = float(r.count)
                cc = dict(bean.get("clusterConfig") or {})
                cc["flowId"] = int(fid)
                cc["thresholdType"] = int(r.threshold_type)
                bean["clusterConfig"] = cc
            else:
                bean = {"resource": str(fid), "count": float(r.count),
                        "clusterMode": True,
                        "clusterConfig": {
                            "flowId": int(fid),
                            "thresholdType": int(r.threshold_type)}}
                if param:
                    bean["grade"] = 1
            if param:
                # per-item thresholds are enforcement too: always rebuilt
                # from the engine rule, never served from the stale bean
                # (classType display strings are kept from the pushed bean
                # when the item survives)
                ctypes = {str(it.get("object")): it.get("classType")
                          for it in bean.get("paramFlowItemList", [])}
                items = getattr(r, "items", None)
                if items:
                    bean["paramFlowItemList"] = [
                        {"object": str(k), "count": float(v),
                         "classType": ctypes.get(str(k),
                                                 type(k).__name__)}
                        for k, v in items.items()]
                else:
                    bean.pop("paramFlowItemList", None)
            beans.append(bean)
        # non-cluster-mode beans pushed through modify are not enforced by
        # the cluster engine but must still round-trip verbatim (the
        # reference stores full FlowRule beans)
        for d in raw_list:
            fid = (d.get("clusterConfig") or {}).get("flowId")
            if fid is None or not d.get("clusterMode"):
                beans.append(d)
        return beans

    def cmd_fetch_flow_rules(self, req: CommandRequest) -> CommandResponse:
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("empty namespace", 400)
        return CommandResponse.of_success(
            json.dumps(self._engine_rule_beans(ns, param=False)))

    def cmd_modify_flow_rules(self, req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.parallel.cluster import ClusterFlowRule
        from sentinel_tpu.rules import codec
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("empty namespace", 400)
        data = req.param("data") or (req.body.decode("utf-8")
                                     if req.body else "")
        if not data.strip():
            return CommandResponse.of_failure("empty data", 400)
        eng, fail = self._engine_or_fail()
        if fail:
            return fail
        try:
            flow_rules = codec.rules_from_json("flow", data)
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(
                f"decode cluster flow rules error: {exc}", 400)
        crules = [ClusterFlowRule(
            flow_id=f.cluster_flow_id, count=f.count,
            threshold_type=f.cluster_threshold_type)
            for f in flow_rules if f.cluster_mode]
        eng.load_rules(ns, crules)
        self._raw_flow[ns] = json.loads(codec.rules_to_json(
            "flow", flow_rules))
        return CommandResponse.of_success(SUCCESS)

    def cmd_fetch_param_rules(self, req: CommandRequest) -> CommandResponse:
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("empty namespace", 400)
        return CommandResponse.of_success(
            json.dumps(self._engine_rule_beans(ns, param=True)))

    def cmd_modify_param_rules(self, req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.parallel.cluster import ClusterParamFlowRule
        from sentinel_tpu.rules import codec
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("empty namespace", 400)
        data = req.param("data") or (req.body.decode("utf-8")
                                     if req.body else "")
        if not data.strip():
            return CommandResponse.of_failure("empty data", 400)
        eng, fail = self._engine_or_fail()
        if fail:
            return fail
        try:
            prules = codec.rules_from_json("paramFlow", data)
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(
                f"decode cluster param rules error: {exc}", 400)
        crules = [ClusterParamFlowRule(
            flow_id=p.cluster_flow_id, count=p.count,
            items={it.object: float(it.count)
                   for it in p.param_flow_item_list} or None)
            for p in prules if p.cluster_mode]
        eng.load_param_rules(ns, crules)
        self._raw_param[ns] = json.loads(codec.rules_to_json(
            "paramFlow", prules))
        return CommandResponse.of_success(SUCCESS)

    # ------------------------------------------------------------ config
    def cmd_fetch_config(self, req: CommandRequest) -> CommandResponse:
        eng = self._resolve_engine()
        srv = self._resolve_server()
        flow_cfg = {"exceedCount": 1.0, "maxOccupyRatio": 1.0,
                    "intervalMs": 1000, "sampleCount": 10}
        if eng is not None:
            w = eng.spec.window
            flow_cfg["intervalMs"] = int(w.win_ms * w.buckets)
            flow_cfg["sampleCount"] = int(w.buckets)
        ns = req.param("namespace")
        if ns:
            if eng is not None:
                # read-only: must not allocate a namespace slot for typos
                flow_cfg["maxAllowedQps"] = eng.namespace_qps_limit(
                    ns, create=False)
            return CommandResponse.of_success(json.dumps({"flow": flow_cfg}))
        out = {"flow": flow_cfg, "namespaceSet": list(self._namespace_set)}
        if srv is not None:
            out["transport"] = {"port": srv.port,
                                "idleSeconds": srv.idle_seconds}
        return CommandResponse.of_success(json.dumps(out))

    def cmd_modify_transport_config(self,
                                    req: CommandRequest) -> CommandResponse:
        srv = self._resolve_server()
        if srv is None:
            return CommandResponse.of_failure("token server not running", 400)
        data = req.param("data") or (req.body.decode("utf-8")
                                     if req.body else "")
        try:
            cfg = json.loads(data or "{}")
            port = cfg.get("port")
            idle = cfg.get("idleSeconds")
            srv.update_transport_config(
                port=int(port) if port is not None else None,
                idle_seconds=float(idle) if idle is not None else None)
        except (ValueError, TypeError, RuntimeError) as exc:
            return CommandResponse.of_failure(
                f"modify transport config failed: {exc}", 400)
        return CommandResponse.of_success(SUCCESS)

    def cmd_modify_flow_config(self, req: CommandRequest) -> CommandResponse:
        """Per-namespace ``ServerFlowConfig`` — ``maxAllowedQps`` feeds the
        GlobalRequestLimiter analog; window geometry is fixed by the engine
        spec (a live geometry change would recompile the sharded step)."""
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("empty namespace", 400)
        eng, fail = self._engine_or_fail()
        if fail:
            return fail
        data = req.param("data") or (req.body.decode("utf-8")
                                     if req.body else "")
        try:
            cfg = json.loads(data or "{}")
            if "maxAllowedQps" in cfg:
                eng.set_namespace_qps_limit(ns, float(cfg["maxAllowedQps"]))
        except (ValueError, TypeError) as exc:
            return CommandResponse.of_failure(
                f"modify flow config failed: {exc}", 400)
        return CommandResponse.of_success(SUCCESS)

    def cmd_modify_namespace_set(self,
                                 req: CommandRequest) -> CommandResponse:
        eng, fail = self._engine_or_fail()
        if fail:
            return fail
        data = req.param("data") or (req.body.decode("utf-8")
                                     if req.body else "")
        try:
            namespaces = json.loads(data or "[]")
            if not isinstance(namespaces, list):
                raise ValueError("expected a JSON list of namespaces")
            for ns in namespaces:
                eng.namespace_id(str(ns))       # pre-register the slot
        except (ValueError, TypeError) as exc:
            return CommandResponse.of_failure(
                f"modify namespace set failed: {exc}", 400)
        self._namespace_set = [str(n) for n in namespaces]
        return CommandResponse.of_success(SUCCESS)

    # ------------------------------------------------------------ info
    def cmd_info(self, req: CommandRequest) -> CommandResponse:
        out: dict = {}
        if self.coordinator is not None:
            out.update(self.coordinator.info())
        srv = self._resolve_server()
        eng = self._resolve_engine()
        if srv is not None:
            out.update(port=srv.port, idleSeconds=srv.idle_seconds,
                       connectedCount=len(getattr(srv, "_conns", ())))
        if eng is not None:
            out["namespaceSet"] = self._namespace_set
        return CommandResponse.of_success(json.dumps(out))

    def cmd_metric_list(self, req: CommandRequest) -> CommandResponse:
        """Current-window metric per flow of the namespace
        (``ClusterMetricNodeGenerator.generateCurrentNodeMap``)."""
        ns = self._need(req, "namespace")
        if ns is None:
            return CommandResponse.of_failure("namespace cannot be empty",
                                              400)
        eng, fail = self._engine_or_fail()
        if fail:
            return fail
        now = self._now_ms()
        names = {}
        for d in (self._engine_rule_beans(ns, param=False)
                  + self._engine_rule_beans(ns, param=True)):
            fid = (d.get("clusterConfig") or {}).get("flowId")
            if fid is not None:
                names[int(fid)] = d.get("resource", "")
        nodes = []
        for fid in eng.namespace_flow_ids(ns):
            m = eng.flow_metrics(fid, now_ms=now)
            if not m:
                continue
            w = eng.spec.window
            secs = max(w.win_ms * w.buckets / 1000.0, 1e-9)
            nodes.append({
                "timestamp": now, "flowId": fid,
                "resourceName": names.get(fid, str(fid)),
                "passQps": round(m.get("pass", 0) / secs, 2),
                "blockQps": round(m.get("block", 0) / secs, 2),
                "rt": 0,
                "topParams": {str(k): v for k, v in
                              eng.top_params(fid, now_ms=now).items()},
            })
        return CommandResponse.of_success(json.dumps(nodes))


def register_cluster_server_handlers(
        center: CommandCenter, *, engine=None, server=None,
        coordinator=None, clock=None) -> ClusterServerCommands:
    cmds = ClusterServerCommands(engine=engine, server=server,
                                 coordinator=coordinator, clock=clock)
    for name, desc, fn in [
        ("cluster/server/flowRules", "get cluster flow rules",
         cmds.cmd_fetch_flow_rules),
        ("cluster/server/modifyFlowRules", "modify cluster flow rules",
         cmds.cmd_modify_flow_rules),
        ("cluster/server/paramRules", "get cluster server param flow rules",
         cmds.cmd_fetch_param_rules),
        ("cluster/server/modifyParamRules",
         "modify cluster param flow rules", cmds.cmd_modify_param_rules),
        ("cluster/server/fetchConfig", "get cluster server config",
         cmds.cmd_fetch_config),
        ("cluster/server/modifyTransportConfig",
         "modify cluster server transport config",
         cmds.cmd_modify_transport_config),
        ("cluster/server/modifyFlowConfig",
         "modify cluster server flow config", cmds.cmd_modify_flow_config),
        ("cluster/server/modifyNamespaceSet",
         "modify server namespace set", cmds.cmd_modify_namespace_set),
        ("cluster/server/info", "get cluster server info", cmds.cmd_info),
        ("cluster/server/metricList", "get cluster server metrics",
         cmds.cmd_metric_list),
    ]:
        center.register(fn, name, desc)
    return cmds
