"""The TPU-host cluster token server.

Reference: ``SentinelDefaultTokenServer`` + ``NettyTransportServer`` +
``TokenServerHandler`` + ``ConnectionManager`` (sentinel-cluster-server-default,
SURVEY §2.3/§3.3). The host process fronts the sharded device engine
(:class:`sentinel_tpu.parallel.cluster.ClusterEngine`): requests arriving
within a small batching window are decided in ONE device step — the wire
protocol is the reference's exact binary framing, so Java Sentinel clients
can point at this server unchanged.

Pieces:

* asyncio TCP server (default port 18730) speaking the framed codec;
* PING → namespace registration (``ConnectionManager.addConnection``), which
  feeds per-namespace ``connectedCount`` into AVG_LOCAL thresholds;
* FLOW / PARAM_FLOW → micro-batched into ``engine.request_tokens`` /
  ``request_param_tokens`` (the batcher is the TPU answer to per-request
  Netty handlers: decisions amortize the host→device hop);
* CONCURRENT acquire/release → host :class:`ConcurrentTokenManager`, with a
  periodic lease sweep (``RegularExpireStrategy``);
* idle-connection reaper (``ScanIdleConnectionTask``).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sentinel_tpu.cluster import codec
from sentinel_tpu.core.clock import Clock
from sentinel_tpu.parallel.cluster import (
    ClusterEngine, ClusterFlowRule, ClusterParamFlowRule,
)
from sentinel_tpu.parallel.concurrent import (
    ConcurrentFlowRule, ConcurrentTokenManager,
)

DEFAULT_IDLE_SECONDS = 600          # ServerTransportConfig default idleSeconds
DEFAULT_BATCH_WINDOW_MS = 1.0       # micro-batch collection window
DEFAULT_EXPIRE_SWEEP_MS = 1000


class _Conn:
    def __init__(self, writer: asyncio.StreamWriter, peer: str):
        self.writer = writer
        self.peer = peer
        self.namespace: Optional[str] = None
        self.last_active = time.monotonic()


class ClusterTokenServer:
    """Standalone (or embedded-alongside-app) token server.

    ``embedded`` mode in the reference means the server shares a JVM with a
    client app (``SentinelDefaultTokenServer.embedded``); here it simply means
    constructing this object inside an app process — there is no separate
    binary.
    """

    def __init__(self, engine: ClusterEngine,
                 concurrent: Optional[ConcurrentTokenManager] = None,
                 *, clock: Optional[Clock] = None,
                 host: str = "0.0.0.0",
                 port: int = codec.DEFAULT_CLUSTER_SERVER_PORT,
                 idle_seconds: float = DEFAULT_IDLE_SECONDS,
                 batch_window_ms: float = DEFAULT_BATCH_WINDOW_MS,
                 log_dir: Optional[str] = None):
        if getattr(engine, "_multiprocess", False):
            # Socket-driven stepping from ONE process would leave the
            # other hosts out of the collective and deadlock the mesh;
            # multi-process serving must route every step through the
            # collective ingest path on all processes instead.
            raise ValueError(
                "ClusterTokenServer cannot front an engine on a "
                "multi-process mesh; drive it with "
                "sentinel_tpu.multihost.MultihostIngest on every process")
        self.engine = engine
        self.concurrent = concurrent or ConcurrentTokenManager()
        self.clock = clock or Clock()
        self.host = host
        self.port = port
        self.idle_seconds = idle_seconds
        self.batch_window_ms = batch_window_ms
        # ClusterServerStatLogUtil → cluster-server.log: per-second rollup
        # of grant/deny counts per flow id (EagleEye StatLogger analog;
        # file IO rides the async appender's flush daemon)
        from sentinel_tpu.core.logs import BlockStatLogger
        self.stat_log = BlockStatLogger(
            self.clock, base_dir=log_dir,
            file_name="sentinel-cluster-server.log")

        self._conns: Set[_Conn] = set()
        self._ns_conns: Dict[str, Set[str]] = {}
        self._concurrent_ns: Dict[str, Set[int]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stopping = False
        # start-attempt epoch: a boot thread abandoned by start()'s timeout
        # must not publish its loop/server over a newer attempt's (the
        # transport-config rollback would otherwise signal the wrong loop)
        self._epoch = 0
        self._state_lock = threading.Lock()
        # micro-batch queues: (request, conn, future-resolution callback)
        self._flow_q: List[Tuple[codec.Request, _Conn]] = []
        self._param_q: List[Tuple[codec.Request, _Conn]] = []
        self._q_event: Optional[asyncio.Event] = None

    # ------------------------------------------------------------------
    # Rule management passthroughs (ClusterFlowRuleManager surface)
    # ------------------------------------------------------------------

    def load_flow_rules(self, namespace: str,
                        rules: Sequence[ClusterFlowRule]) -> None:
        self.engine.load_rules(namespace, rules)

    def load_param_rules(self, namespace: str,
                         rules: Sequence[ClusterParamFlowRule]) -> None:
        self.engine.load_param_rules(namespace, rules)

    def load_concurrent_rules(self, namespace: str,
                              rules: Sequence[ConcurrentFlowRule]) -> None:
        self._concurrent_ns[namespace] = {r.flow_id for r in rules}
        self.concurrent.load_rules(rules)
        self._sync_connected(namespace)

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def update_transport_config(self, port: Optional[int] = None,
                                idle_seconds: Optional[float] = None) -> None:
        """Live transport-config change — the ``ServerTransportConfig``
        watcher (``SentinelDefaultTokenServer.java:37-111``: the reference
        stops and restarts the netty server when the port changes). An
        idle-seconds change applies immediately (the reaper reads it per
        sweep); a port change restarts the listener, dropping connections
        exactly like the reference restart — clients re-register via their
        2 s reconnect loop."""
        if idle_seconds is not None:
            self.idle_seconds = float(idle_seconds)
        if port is not None and int(port) != self.port:
            running = self._thread is not None
            old_port = self.port
            if running:
                self.stop()
            self.port = int(port)
            if running:
                try:
                    self.start()
                except Exception:
                    # the new port didn't bind: restore service on the old
                    # one rather than staying down (clients are still
                    # reconnecting to it)
                    self._thread = None
                    self._loop = None
                    self.port = old_port
                    self.start()
                    raise

    def start(self) -> None:
        """Run the server on a daemon thread; returns once listening. A bind
        failure (port in use) surfaces immediately — the boot exception is
        handed back through ``_boot_error`` rather than waiting out the
        10 s timeout, so a transport-config restart's rollback window stays
        at milliseconds."""
        if self._thread is not None:
            return
        self._boot_error: Optional[BaseException] = None
        epoch = self._epoch
        self._thread = threading.Thread(target=self._run, args=(epoch,),
                                        daemon=True,
                                        name="sentinel-cluster-server")
        self._thread.start()
        if not self._started.wait(timeout=10):
            with self._state_lock:
                self._epoch += 1     # the late boot must not publish
            self._thread = None
            raise RuntimeError("cluster token server failed to start")
        if self._boot_error is not None:
            self._thread.join(timeout=1)
            self._thread = None
            self._loop = None
            self._started.clear()
            exc, self._boot_error = self._boot_error, None
            raise RuntimeError(
                f"cluster token server failed to start: {exc}") from exc

    def stop(self) -> None:
        if self._loop is None:
            return
        self._stopping = True
        loop = self._loop
        fut = asyncio.run_coroutine_threadsafe(self._shutdown(), loop)
        fut.result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        if self._thread:
            self._thread.join(timeout=10)
        self._thread = None
        self._loop = None
        self._started.clear()
        self._stopping = False

    async def _shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        for c in list(self._conns):
            c.writer.close()
        await asyncio.sleep(0)  # let handler tasks observe the closes

    def _run(self, epoch: int) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            return await asyncio.start_server(
                self._handle_conn, self.host, self.port)

        try:
            server = loop.run_until_complete(boot())
        except BaseException as exc:    # bind failure → report, clean up
            with self._state_lock:
                if self._epoch == epoch:
                    self._boot_error = exc
                    self._started.set()
            loop.close()
            return
        with self._state_lock:
            if self._epoch != epoch:
                # start() timed this attempt out and moved on (e.g. the
                # rollback server is already up) — release the socket and
                # vanish without touching published state
                abandoned = True
            else:
                abandoned = False
                self._loop = loop
                self._server = server
                self._q_event = asyncio.Event()
                if self.port == 0:
                    self.port = server.sockets[0].getsockname()[1]
        if abandoned:
            server.close()
            try:
                loop.run_until_complete(server.wait_closed())
            except Exception:
                pass
            loop.close()
            return
        loop.create_task(self._batch_loop())
        loop.create_task(self._sweep_loop())
        loop.create_task(self._idle_loop())
        self._started.set()
        try:
            loop.run_forever()
        finally:
            for task in asyncio.all_tasks(loop):
                task.cancel()
            try:
                loop.run_until_complete(asyncio.sleep(0))
            except Exception:
                pass
            loop.close()

    # ------------------------------------------------------------------
    # Connection handling
    # ------------------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        peer = "%s:%s" % (writer.get_extra_info("peername") or ("?", 0))[:2]
        conn = _Conn(writer, peer)
        self._conns.add(conn)
        assembler = codec.FrameAssembler()
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    break
                conn.last_active = time.monotonic()
                for frame in assembler.feed(data):
                    await self._dispatch(frame, conn)
        except (ConnectionResetError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            self._drop_conn(conn)
            writer.close()

    def _drop_conn(self, conn: _Conn) -> None:
        self._conns.discard(conn)
        if conn.namespace is not None:
            group = self._ns_conns.get(conn.namespace)
            if group is not None:
                group.discard(conn.peer)
                self._sync_connected(conn.namespace)

    def _sync_connected(self, namespace: str) -> None:
        count = max(1, len(self._ns_conns.get(namespace, ())))
        self.engine.set_connected_count(namespace, count)
        for fid in self._concurrent_ns.get(namespace, ()):
            self.concurrent.set_connected_count(fid, count)

    async def _dispatch(self, frame: bytes, conn: _Conn) -> None:
        try:
            req = codec.decode_request(frame)
        except Exception:
            # malformed payload (bad TLV, truncated data): the reference's
            # decoder just drops the frame; subsequent frames stay usable
            return
        if req is None:
            return
        t = req.type
        if t == codec.MSG_TYPE_PING:
            ns = str(req.data or "default")
            if conn.namespace is not None and conn.namespace != ns:
                # re-registration: leave the old namespace group first
                old = self._ns_conns.get(conn.namespace)
                if old is not None:
                    old.discard(conn.peer)
                    self._sync_connected(conn.namespace)
            conn.namespace = ns
            self._ns_conns.setdefault(ns, set()).add(conn.peer)
            self._sync_connected(ns)
            await self._send(conn, codec.Response(
                req.xid, t, codec.RESPONSE_STATUS_OK,
                len(self._ns_conns.get(ns, ()))))
        elif t == codec.MSG_TYPE_FLOW:
            self._flow_q.append((req, conn))
            self._q_event.set()
        elif t == codec.MSG_TYPE_PARAM_FLOW:
            self._param_q.append((req, conn))
            self._q_event.set()
        elif t == codec.MSG_TYPE_CONCURRENT_FLOW_ACQUIRE:
            flow_id, count, _prio = req.data
            status, token_id = self.concurrent.acquire(
                flow_id, count, client_address=conn.peer,
                now_ms=self.clock.now_ms())
            await self._send(conn, codec.Response(req.xid, t, status, token_id))
        elif t == codec.MSG_TYPE_CONCURRENT_FLOW_RELEASE:
            status = self.concurrent.release(int(req.data))
            await self._send(conn, codec.Response(req.xid, t, status))
        else:
            await self._send(conn, codec.Response(
                req.xid, t, codec.RESPONSE_STATUS_BAD))

    async def _send(self, conn: _Conn, resp: codec.Response) -> None:
        try:
            conn.writer.write(codec.encode_response(resp))
            await conn.writer.drain()
        except (ConnectionResetError, RuntimeError):
            self._drop_conn(conn)

    # ------------------------------------------------------------------
    # Micro-batched token decisions
    # ------------------------------------------------------------------

    async def _batch_loop(self) -> None:
        while True:
            await self._q_event.wait()
            # collect for one batching window, then decide in one device step
            if self.batch_window_ms > 0:
                await asyncio.sleep(self.batch_window_ms / 1000.0)
            self._q_event.clear()
            flow_q, self._flow_q = self._flow_q, []
            param_q, self._param_q = self._param_q, []
            now_ms = self.clock.now_ms()
            if flow_q:
                reqs = [r for r, _ in flow_q]
                res = await asyncio.to_thread(
                    self.engine.request_tokens,
                    [r.data[0] for r in reqs], [r.data[1] for r in reqs],
                    [r.data[2] for r in reqs], now_ms=now_ms)
                for (req, conn), (status, wait_ms, remaining) in zip(flow_q, res):
                    self.stat_log.log(f"flow-{req.data[0]}",
                                      "pass" if status in (0, 2) else "block",
                                      origin=conn.namespace or "")
                    await self._send(conn, codec.Response(
                        req.xid, req.type, status, (remaining, wait_ms)))
            if param_q:
                reqs = [r for r, _ in param_q]
                res = await asyncio.to_thread(
                    self.engine.request_param_tokens,
                    [r.data[0] for r in reqs], [r.data[1] for r in reqs],
                    [r.data[2] for r in reqs], now_ms=now_ms)
                for (req, conn), (status, wait_ms, remaining) in zip(param_q, res):
                    self.stat_log.log(f"param-{req.data[0]}",
                                      "pass" if status in (0, 2) else "block",
                                      origin=conn.namespace or "")
                    await self._send(conn, codec.Response(
                        req.xid, req.type, status, (remaining, wait_ms)))

    async def _sweep_loop(self) -> None:
        """RegularExpireStrategy: reclaim expired concurrent leases."""
        while True:
            await asyncio.sleep(DEFAULT_EXPIRE_SWEEP_MS / 1000.0)
            self.concurrent.sweep_expired(now_ms=self.clock.now_ms())

    async def _idle_loop(self) -> None:
        """ScanIdleConnectionTask: close connections idle beyond the limit."""
        while True:
            await asyncio.sleep(min(30.0, self.idle_seconds / 2 + 0.01))
            cutoff = time.monotonic() - self.idle_seconds
            for c in list(self._conns):
                if c.last_active < cutoff:
                    c.writer.close()
                    self._drop_conn(c)

    # ------------------------------------------------------------------
    def connection_count(self, namespace: str) -> int:
        return len(self._ns_conns.get(namespace, ()))
