"""Cluster token transport: reference-compatible wire protocol, TCP token
server, and client SDK (SURVEY §2.3, sentinel-cluster-*)."""

from sentinel_tpu.cluster.codec import (  # noqa: F401
    MSG_TYPE_PING, MSG_TYPE_FLOW, MSG_TYPE_PARAM_FLOW,
    MSG_TYPE_CONCURRENT_FLOW_ACQUIRE, MSG_TYPE_CONCURRENT_FLOW_RELEASE,
    DEFAULT_CLUSTER_SERVER_PORT, DEFAULT_REQUEST_TIMEOUT_MS,
    FrameAssembler, Request, Response,
    decode_request, decode_response, encode_request, encode_response,
)
from sentinel_tpu.cluster.server import ClusterTokenServer  # noqa: F401
from sentinel_tpu.cluster.client import ClusterTokenClient, TokenResult  # noqa: F401
