"""Cluster wire protocol codec — byte-compatible with the reference.

Frame layout (``sentinel-cluster-common-default``; server pipeline
``NettyTransportServer``: ``LengthFieldBasedFrameDecoder(1024, 0, 2, 0, 2)`` +
2-byte ``LengthFieldPrepender``):

    [len:2 BE (body only)] [body]

Request body (``DefaultRequestEntityDecoder.java``):

    [xid:4 BE] [type:1] [data]

Response body (``DefaultResponseEntityWriter.writeHead``):

    [xid:4 BE] [type:1] [status:1 signed] [data]

Data payloads:

* PING (type 0): request = ``[nsLen:4 BE][namespace utf-8]``
  (``PingRequestDataDecoder.java``); response = ``[curCount:4 BE]``
  (``PingResponseDataWriter.java``).
* FLOW (type 1): request = ``[flowId:8 BE][count:4 BE][priority:1]``
  (``FlowRequestDataDecoder.java``); response =
  ``[remaining:4 BE][waitInMs:4 BE]`` (``FlowResponseDataWriter.java``).
* PARAM_FLOW (type 2): request = ``[flowId:8][count:4][amount:4][TLV × amount]``
  with TLV tags int=0/long=1/byte=2/double=3/float=4/short=5/bool=6/string=7
  (string = ``[7][len:4][utf-8]``) — ``ParamFlowRequestDataDecoder.java``,
  ``ClusterConstants.java:34-41``; response same as FLOW.
* CONCURRENT_FLOW_ACQUIRE (type 3) / _RELEASE (type 4): the reference defines
  the message type ids (``ClusterConstants.java:27-28``) but ships no client
  codec for them in 1.8.6 — this framework completes the pair as a documented
  extension: acquire request = ``[flowId:8][count:4][prioritized:1]``,
  acquire response = ``[tokenId:8]``; release request = ``[tokenId:8]``,
  release response = empty.

The response ``status`` byte carries ``TokenResultStatus`` codes
(``sentinel_tpu.parallel.cluster.STATUS_*``), signed.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Sequence, Tuple

MSG_TYPE_PING = 0
MSG_TYPE_FLOW = 1
MSG_TYPE_PARAM_FLOW = 2
MSG_TYPE_CONCURRENT_FLOW_ACQUIRE = 3
MSG_TYPE_CONCURRENT_FLOW_RELEASE = 4

RESPONSE_STATUS_BAD = -1
RESPONSE_STATUS_OK = 0

DEFAULT_CLUSTER_SERVER_PORT = 18730
DEFAULT_REQUEST_TIMEOUT_MS = 20
MAX_FRAME_BYTES = 1024

PARAM_TYPE_INTEGER = 0
PARAM_TYPE_LONG = 1
PARAM_TYPE_BYTE = 2
PARAM_TYPE_DOUBLE = 3
PARAM_TYPE_FLOAT = 4
PARAM_TYPE_SHORT = 5
PARAM_TYPE_BOOLEAN = 6
PARAM_TYPE_STRING = 7


@dataclasses.dataclass
class Request:
    xid: int
    type: int
    # decoded payload per type: PING → namespace str; FLOW → (flow_id, count,
    # prioritized); PARAM_FLOW → (flow_id, count, params list);
    # CONCURRENT acquire → (flow_id, count, prioritized); release → token_id
    data: object


@dataclasses.dataclass
class Response:
    xid: int
    type: int
    status: int
    # payload per type: PING → int; FLOW/PARAM_FLOW → (remaining, wait_ms);
    # CONCURRENT acquire → token_id; release → None
    data: object = None


# ----------------------------------------------------------------------
# TLV params
# ----------------------------------------------------------------------

def _encode_param(out: bytearray, value: object) -> None:
    if isinstance(value, bool):           # before int: bool is an int subtype
        out.append(PARAM_TYPE_BOOLEAN)
        out.append(1 if value else 0)
    elif isinstance(value, int):
        if -2 ** 31 <= value < 2 ** 31:
            out.append(PARAM_TYPE_INTEGER)
            out += struct.pack(">i", value)
        else:
            out.append(PARAM_TYPE_LONG)
            out += struct.pack(">q", value)
    elif isinstance(value, float):
        out.append(PARAM_TYPE_DOUBLE)
        out += struct.pack(">d", value)
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(PARAM_TYPE_STRING)
        out += struct.pack(">i", len(raw))
        out += raw
    else:
        raise TypeError(f"unsupported param type: {type(value).__name__}")


def _decode_param(buf: memoryview, off: int) -> Tuple[object, int]:
    tag = buf[off]
    off += 1
    if tag == PARAM_TYPE_INTEGER:
        return struct.unpack_from(">i", buf, off)[0], off + 4
    if tag == PARAM_TYPE_LONG:
        return struct.unpack_from(">q", buf, off)[0], off + 8
    if tag == PARAM_TYPE_BYTE:
        return struct.unpack_from(">b", buf, off)[0], off + 1
    if tag == PARAM_TYPE_DOUBLE:
        return struct.unpack_from(">d", buf, off)[0], off + 8
    if tag == PARAM_TYPE_FLOAT:
        return struct.unpack_from(">f", buf, off)[0], off + 4
    if tag == PARAM_TYPE_SHORT:
        return struct.unpack_from(">h", buf, off)[0], off + 2
    if tag == PARAM_TYPE_BOOLEAN:
        return buf[off] != 0, off + 1
    if tag == PARAM_TYPE_STRING:
        n = struct.unpack_from(">i", buf, off)[0]
        off += 4
        return bytes(buf[off:off + n]).decode("utf-8"), off + n
    raise ValueError(f"unknown param TLV tag {tag}")


# ----------------------------------------------------------------------
# Request / response bodies
# ----------------------------------------------------------------------

def encode_request(req: Request) -> bytes:
    body = bytearray(struct.pack(">ib", req.xid, req.type))
    t = req.type
    if t == MSG_TYPE_PING:
        raw = str(req.data or "").encode("utf-8")
        body += struct.pack(">i", len(raw))
        body += raw
    elif t in (MSG_TYPE_FLOW, MSG_TYPE_CONCURRENT_FLOW_ACQUIRE):
        flow_id, count, prioritized = req.data
        body += struct.pack(">qib", flow_id, count, 1 if prioritized else 0)
    elif t == MSG_TYPE_PARAM_FLOW:
        flow_id, count, params = req.data
        body += struct.pack(">qii", flow_id, count, len(params))
        for v in params:
            _encode_param(body, v)
    elif t == MSG_TYPE_CONCURRENT_FLOW_RELEASE:
        body += struct.pack(">q", req.data)
    else:
        raise ValueError(f"unknown request type {t}")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(body)}")
    return struct.pack(">H", len(body)) + bytes(body)


def decode_request(body: bytes) -> Optional[Request]:
    if len(body) < 5:
        return None
    xid, t = struct.unpack_from(">ib", body, 0)
    mv = memoryview(body)
    off = 5
    if t == MSG_TYPE_PING:
        if len(body) < off + 4:
            return Request(xid, t, "")
        n = struct.unpack_from(">i", mv, off)[0]
        ns = bytes(mv[off + 4:off + 4 + n]).decode("utf-8") if n > 0 else ""
        return Request(xid, t, ns)
    if t in (MSG_TYPE_FLOW, MSG_TYPE_CONCURRENT_FLOW_ACQUIRE):
        if len(body) < off + 12:
            return None
        flow_id, count = struct.unpack_from(">qi", mv, off)
        prio = body[off + 12] != 0 if len(body) > off + 12 else False
        return Request(xid, t, (flow_id, count, prio))
    if t == MSG_TYPE_PARAM_FLOW:
        if len(body) < off + 16:
            return None
        flow_id, count, amount = struct.unpack_from(">qii", mv, off)
        off += 16
        params: List[object] = []
        for _ in range(max(0, amount)):
            v, off = _decode_param(mv, off)
            params.append(v)
        return Request(xid, t, (flow_id, count, params))
    if t == MSG_TYPE_CONCURRENT_FLOW_RELEASE:
        if len(body) < off + 8:
            return None
        return Request(xid, t, struct.unpack_from(">q", mv, off)[0])
    return Request(xid, t, None)  # unknown type → server answers BAD


def encode_response(resp: Response) -> bytes:
    body = bytearray(struct.pack(">ibb", resp.xid, resp.type, resp.status))
    t = resp.type
    if t == MSG_TYPE_PING:
        body += struct.pack(">i", int(resp.data or 0))
    elif t in (MSG_TYPE_FLOW, MSG_TYPE_PARAM_FLOW):
        remaining, wait_ms = resp.data if resp.data is not None else (0, 0)
        body += struct.pack(">ii", remaining, wait_ms)
    elif t == MSG_TYPE_CONCURRENT_FLOW_ACQUIRE:
        body += struct.pack(">q", int(resp.data or 0))
    # RELEASE and unknown types: head only
    return struct.pack(">H", len(body)) + bytes(body)


def decode_response(body: bytes) -> Optional[Response]:
    if len(body) < 6:
        return None
    xid, t, status = struct.unpack_from(">ibb", body, 0)
    off = 6
    if t == MSG_TYPE_PING and len(body) >= off + 4:
        return Response(xid, t, status, struct.unpack_from(">i", body, off)[0])
    if t in (MSG_TYPE_FLOW, MSG_TYPE_PARAM_FLOW) and len(body) >= off + 8:
        return Response(xid, t, status,
                        tuple(struct.unpack_from(">ii", body, off)))
    if t == MSG_TYPE_CONCURRENT_FLOW_ACQUIRE and len(body) >= off + 8:
        return Response(xid, t, status, struct.unpack_from(">q", body, off)[0])
    return Response(xid, t, status, None)


class FrameAssembler:
    """Stream reassembly of 2-byte length-prefixed frames
    (LengthFieldBasedFrameDecoder semantics; max body 1024)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        frames: List[bytes] = []
        while True:
            if len(self._buf) < 2:
                return frames
            n = struct.unpack_from(">H", self._buf, 0)[0]
            if n > MAX_FRAME_BYTES:
                raise ValueError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
            if len(self._buf) < 2 + n:
                return frames
            frames.append(bytes(self._buf[2:2 + n]))
            del self._buf[:2 + n]
