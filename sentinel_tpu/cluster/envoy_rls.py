"""Envoy Rate Limit Service v3 backed by the cluster token engine
(reference ``sentinel-cluster/sentinel-cluster-server-envoy-rls``:
``SentinelEnvoyRlsServiceImpl.java`` + ``EnvoySentinelRuleConverter.java`` +
``SentinelRlsGrpcServer.java``).

Descriptor semantics match the reference: each rule names a ``domain`` and an
ordered list of descriptor (key, value) pairs; a request descriptor maps to
the flow id derived from the identifier ``domain|k1:v1|k2:v2``; an unmatched
descriptor passes (no rule ⇒ OK); any BLOCKED descriptor makes the overall
code OVER_LIMIT. Token accounting runs on the sharded
:class:`~sentinel_tpu.parallel.cluster.ClusterEngine` exactly like the Netty
token path — the RLS frontend is just another protocol speaking to the same
checkers (``SimpleClusterFlowChecker`` in the reference is a trimmed acquire
of the same ``ClusterFlowChecker`` state).

The gRPC message classes are compiled from a trimmed wire-compatible subset
of the upstream protos (``proto/envoy_rls.proto``); the service is wired with
``grpc.method_handlers_generic_handler`` (no grpc codegen plugin needed).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core.clock import Clock
from sentinel_tpu.parallel.cluster import (
    STATUS_NO_RULE_EXISTS, STATUS_OK, THRESHOLD_GLOBAL,
    ClusterEngine, ClusterFlowRule,
)

SEPARATOR = "|"           # EnvoySentinelRuleConverter.SEPARATOR

RLS_METHOD = "/envoy.service.ratelimit.v3.RateLimitService/ShouldRateLimit"

CODE_UNKNOWN = 0          # RateLimitResponse.Code
CODE_OK = 1
CODE_OVER_LIMIT = 2
UNIT_SECOND = 1           # RateLimitResponse.RateLimit.Unit


def descriptor_identifier(domain: str,
                          entries: Sequence[Tuple[str, str]]) -> str:
    """``domain|k1:v1|k2:v2`` (EnvoySentinelRuleConverter identifier)."""
    parts = [domain] + [f"{k}:{v}" for k, v in entries]
    return SEPARATOR.join(parts)


def identifier_flow_id(identifier: str) -> int:
    """Stable positive 63-bit flow id for an identifier string (the
    reference derives ids by hashing the identifier; any stable injective-
    enough mapping works since rules and requests share it)."""
    h = 1469598103934665603          # FNV-1a 64
    for b in identifier.encode("utf-8"):
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h & 0x7FFFFFFFFFFFFFFF


@dataclasses.dataclass
class RlsDescriptorRule:
    """One limited descriptor: ordered (key, value) pairs + per-second cap."""
    entries: List[Tuple[str, str]]
    count: float


@dataclasses.dataclass
class EnvoyRlsRule:
    """Reference ``EnvoyRlsRule``: domain + limited descriptors."""
    domain: str
    descriptors: List[RlsDescriptorRule]


class EnvoyRlsRuleManager:
    """flow-id table + conversion into cluster rules
    (``EnvoyRlsRuleManager`` + ``EnvoySentinelRuleConverter``)."""

    def __init__(self, engine: ClusterEngine):
        self.engine = engine
        self._lock = threading.Lock()
        self._flow_ids: Dict[str, int] = {}       # identifier → flow id
        self._limits: Dict[int, float] = {}       # flow id → count
        self._loaded_domains: set = set()         # exact domains in engine

    def load_rules(self, rules: Sequence[EnvoyRlsRule]) -> None:
        """Replace all RLS rules (grouped per domain = namespace)."""
        with self._lock:
            by_domain: Dict[str, List[ClusterFlowRule]] = {}
            flow_ids: Dict[str, int] = {}
            limits: Dict[int, float] = {}
            for rule in rules:
                for d in rule.descriptors:
                    ident = descriptor_identifier(rule.domain, d.entries)
                    fid = identifier_flow_id(ident)
                    flow_ids[ident] = fid
                    limits[fid] = d.count
                    by_domain.setdefault(rule.domain, []).append(
                        ClusterFlowRule(
                            flow_id=fid, count=d.count,
                            threshold_type=THRESHOLD_GLOBAL))
            # Apply new/updated domains first; only then clear stale ones and
            # swap the lookup maps. If the engine raises mid-way (e.g.
            # namespace capacity — engine namespace slots are never freed, so
            # domain cardinality is bounded by spec.namespaces), the lookup
            # maps stay on the old, still-loaded rule set; a lookup that
            # races a drop resolves to NO_RULE_EXISTS which reads as OK.
            for domain, crules in by_domain.items():
                self.engine.load_rules(domain, crules)
            # exact loaded-domain set (identifiers can't be split back —
            # domains may themselves contain the separator)
            for stale in (self._loaded_domains - set(by_domain)):
                self.engine.load_rules(stale, [])
            self._loaded_domains = set(by_domain)
            self._flow_ids = flow_ids
            self._limits = limits

    def lookup(self, domain: str,
               entries: Sequence[Tuple[str, str]]) -> Optional[int]:
        with self._lock:
            return self._flow_ids.get(descriptor_identifier(domain, entries))

    def limit_of(self, flow_id: int) -> float:
        with self._lock:
            return self._limits.get(flow_id, 0.0)


@dataclasses.dataclass
class DescriptorStatus:
    code: int
    limit: float = 0.0
    remaining: int = 0


class EnvoyRlsService:
    """Protocol-neutral core of ``shouldRateLimit`` (so it is testable
    without gRPC and reusable behind an HTTP frontend)."""

    def __init__(self, engine: ClusterEngine,
                 rules: Optional[EnvoyRlsRuleManager] = None,
                 clock: Optional[Clock] = None):
        self.engine = engine
        self.rules = rules or EnvoyRlsRuleManager(engine)
        self._clock = clock or Clock()

    def _now_ms(self) -> int:
        return self._clock.now_ms()

    def should_rate_limit(
            self, domain: str,
            descriptors: Sequence[Sequence[Tuple[str, str]]],
            hits_addend: int = 1) -> Tuple[int, List[DescriptorStatus]]:
        acquire = max(1, int(hits_addend))     # 0 → 1 like the reference
        statuses: List[DescriptorStatus] = [None] * len(descriptors)  # type: ignore
        flow_ids: List[int] = []
        positions: List[int] = []
        for i, entries in enumerate(descriptors):
            fid = self.rules.lookup(domain, list(entries))
            if fid is None:
                statuses[i] = DescriptorStatus(code=CODE_OK)   # no rule ⇒ OK
            else:
                flow_ids.append(fid)
                positions.append(i)
        if flow_ids:
            results = self.engine.request_tokens(
                flow_ids, [acquire] * len(flow_ids), now_ms=self._now_ms())
            for (status, _wait, remaining), fid, i in zip(
                    results, flow_ids, positions):
                # SentinelEnvoyRlsServiceImpl: a rule dropped between lookup
                # and token request (NO_RULE_EXISTS) keeps the "no rule ⇒ OK"
                # contract; every OTHER non-OK status (BLOCKED, TOO_MANY,
                # SHOULD_WAIT, FAIL, BAD_REQUEST) is OVER_LIMIT — RLS has no
                # way to honor a wait, and engine errors must not fail open
                blocked = status not in (STATUS_OK, STATUS_NO_RULE_EXISTS)
                statuses[i] = DescriptorStatus(
                    code=CODE_OVER_LIMIT if blocked else CODE_OK,
                    limit=self.rules.limit_of(fid),
                    remaining=max(0, remaining))
        overall = (CODE_OVER_LIMIT
                   if any(s.code == CODE_OVER_LIMIT for s in statuses)
                   else CODE_OK)
        return overall, statuses


class SentinelRlsGrpcServer:
    """gRPC frontend (reference ``SentinelRlsGrpcServer``), default port
    10245 — hand-wired generic handler over the compiled subset protos."""

    DEFAULT_PORT = 10245

    def __init__(self, service: EnvoyRlsService, host: str = "0.0.0.0",
                 port: int = DEFAULT_PORT, max_workers: int = 8):
        self.service = service
        self.host = host
        self.requested_port = port
        self.port: Optional[int] = None
        self._server = None
        self._max_workers = max_workers

    def _handler(self):
        import grpc
        from sentinel_tpu.cluster.proto import envoy_rls_pb2 as pb

        def should_rate_limit(request, context):
            descriptors = [[(e.key, e.value) for e in d.entries]
                           for d in request.descriptors]
            overall, statuses = self.service.should_rate_limit(
                request.domain, descriptors, request.hits_addend or 1)
            resp = pb.RateLimitResponse(overall_code=overall)
            for s in statuses:
                ds = resp.statuses.add()
                ds.code = s.code
                ds.limit_remaining = s.remaining
                if s.limit:
                    ds.current_limit.requests_per_unit = int(s.limit)
                    ds.current_limit.unit = UNIT_SECOND
            return resp

        return grpc.method_handlers_generic_handler(
            "envoy.service.ratelimit.v3.RateLimitService",
            {"ShouldRateLimit": grpc.unary_unary_rpc_method_handler(
                should_rate_limit,
                request_deserializer=pb.RateLimitRequest.FromString,
                response_serializer=pb.RateLimitResponse.SerializeToString)})

    def start(self) -> int:
        import grpc
        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.requested_port}")
        if self.port == 0:
            raise OSError(f"cannot bind RLS port {self.requested_port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=None)
            self._server = None
