"""Batched gRPC token-service frontend (SURVEY §7 phase 3(a)).

The reference serves tokens over two surfaces: the Netty frame protocol
(``TokenServerHandler.java`` — mirrored byte-compatibly by
:mod:`sentinel_tpu.cluster.server`) and a gRPC server for Envoy RLS
(``SentinelRlsGrpcServer.java`` — mirrored by
:mod:`sentinel_tpu.cluster.envoy_rls`). This module is the missing sibling:
a clean batched gRPC API over the same sharded
:class:`~sentinel_tpu.parallel.cluster.ClusterEngine`, so a remote serving
process can fetch a whole batch of verdicts in one RPC the way the
in-process embedded facade does (``DefaultTokenService.requestToken`` /
``requestParamToken`` lifted to batches).

Server::

    srv = TokenGrpcServer(engine, port=0, clock=clock)
    port = srv.start()

Client (the whole integration)::

    cli = GrpcTokenClient(f"127.0.0.1:{port}", timeout_ms=20)
    results = cli.request_tokens_batch([(fid, 1, False), ...])

Deadline → fallback: the client stamps every RPC with its timeout (the
reference budget — ``ClusterConstants.DEFAULT_REQUEST_TIMEOUT`` = 20 ms) and
maps DeadlineExceeded/transport errors to ``STATUS_FAIL`` per item, which is
exactly what the runtime's per-rule ``fallbackToLocalWhenFail`` consumes —
so ``GrpcTokenClient`` plugs straight into ``Sentinel.set_token_service``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from sentinel_tpu.core.clock import Clock
from sentinel_tpu.parallel.cluster import (
    STATUS_BAD_REQUEST, STATUS_FAIL, ClusterEngine,
)

SERVICE_NAME = "sentinel.cluster.v1.TokenService"
DEFAULT_PORT = 11000
# reference ClusterConstants.DEFAULT_REQUEST_TIMEOUT (ms)
DEFAULT_TIMEOUT_MS = 20
# hard cap on per-RPC batch size: a huge batch would stall every other
# caller behind one device step (and a malicious one would OOM the host)
MAX_BATCH = 65536


class TokenGrpcService:
    """Protocol-neutral core (testable without gRPC): a mixed batch splits
    into flow and hot-param sub-batches — each one engine step — and the
    results re-align to request order."""

    def __init__(self, engine: ClusterEngine, clock: Optional[Clock] = None):
        self.engine = engine
        self._clock = clock or Clock()

    def request_tokens(self, items: Sequence[Tuple[int, int, bool,
                                                   Sequence[str]]]
                       ) -> List[Tuple[int, int, int]]:
        """``items``: (flow_id, acquire, prioritized, params) rows →
        aligned (status, wait_ms, remaining) rows."""
        if len(items) > MAX_BATCH:
            return [(STATUS_BAD_REQUEST, 0, 0)] * len(items)
        now = self._clock.now_ms()
        out: List[Optional[Tuple[int, int, int]]] = [None] * len(items)
        flow_idx: List[int] = []
        flow_req: List[Tuple[int, int, bool]] = []
        param_idx: List[int] = []
        param_req: List[Tuple[int, int, List[str]]] = []
        for i, (fid, acquire, prioritized, params) in enumerate(items):
            if acquire <= 0:
                out[i] = (STATUS_BAD_REQUEST, 0, 0)
            elif params:
                param_idx.append(i)
                param_req.append((int(fid), int(acquire), list(params)))
            else:
                flow_idx.append(i)
                flow_req.append((int(fid), int(acquire), bool(prioritized)))
        if flow_req:
            res = self.engine.request_tokens(
                [r[0] for r in flow_req], [r[1] for r in flow_req],
                [r[2] for r in flow_req], now_ms=now)
            for i, r in zip(flow_idx, res):
                out[i] = (int(r[0]), int(r[1]), int(r[2]))
        if param_req:
            res = self.engine.request_param_tokens(
                [r[0] for r in param_req], [r[1] for r in param_req],
                [r[2] for r in param_req], now_ms=now)
            for i, r in zip(param_idx, res):
                out[i] = (int(r[0]), int(r[1]), int(r[2]))
        # A misbehaving engine returning fewer rows than requested must
        # degrade to per-item FAIL (like a transport error), not crash the
        # proto response construction with an opaque RPC error.
        return [(STATUS_FAIL, 0, 0) if r is None else r for r in out]


class TokenGrpcServer:
    """gRPC frontend over :class:`TokenGrpcService` — hand-wired generic
    handler like the RLS server (no grpc codegen plugin needed)."""

    def __init__(self, engine: ClusterEngine, host: str = "0.0.0.0",
                 port: int = DEFAULT_PORT, max_workers: int = 8,
                 clock: Optional[Clock] = None):
        self.service = TokenGrpcService(engine, clock=clock)
        self.host = host
        self.requested_port = port
        self.port: Optional[int] = None
        self._server = None
        self._max_workers = max_workers

    def _handler(self):
        import grpc

        from sentinel_tpu.cluster.proto import token_service_pb2 as pb

        def request_tokens(request, context):
            # acquire passes through raw: 0/negative → STATUS_BAD_REQUEST in
            # the service, matching the engine and Netty surfaces (a proto3
            # default-0 means the client didn't set a count — that's a bad
            # request, not a grant of 1)
            items = [(r.flow_id, r.acquire, r.prioritized,
                      list(r.params)) for r in request.requests]
            resp = pb.BatchTokenResponse()
            for status, wait_ms, remaining in self.service.request_tokens(
                    items):
                resp.responses.add(status=status, wait_ms=wait_ms,
                                   remaining=remaining)
            return resp

        return grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {"RequestTokens": grpc.unary_unary_rpc_method_handler(
                request_tokens,
                request_deserializer=pb.BatchTokenRequest.FromString,
                response_serializer=pb.BatchTokenResponse.SerializeToString)})

    def start(self) -> int:
        import grpc
        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.requested_port}")
        if self.port == 0:
            raise OSError(
                f"cannot bind token-service port {self.requested_port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=None)
            self._server = None


class _GrpcResult:
    """TokenResult-shaped row (duck-typed like the other token services)."""

    __slots__ = ("status", "wait_ms", "remaining")

    def __init__(self, status: int, wait_ms: int = 0, remaining: int = 0):
        self.status = status
        self.wait_ms = wait_ms
        self.remaining = remaining


class GrpcTokenClient:
    """Client speaking the batched API; satisfies the runtime's token-service
    duck type (``request_token`` + ``request_tokens_batch`` +
    ``request_param_token``), so it installs via
    ``Sentinel.set_token_service`` exactly like the Netty client. Every RPC
    carries the deadline; DeadlineExceeded and transport errors map to
    ``STATUS_FAIL`` per item — the caller's per-rule
    ``fallbackToLocalWhenFail`` then checks locally, never fails open."""

    def __init__(self, target: str, namespace: str = "default",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        import grpc

        from sentinel_tpu.cluster.proto import token_service_pb2 as pb
        self._pb = pb
        self.namespace = namespace
        self.timeout_ms = timeout_ms
        self._channel = grpc.insecure_channel(target)
        self._call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/RequestTokens",
            request_serializer=pb.BatchTokenRequest.SerializeToString,
            response_deserializer=pb.BatchTokenResponse.FromString)

    def close(self) -> None:
        self._channel.close()

    # ---------------------------------------------------------- batched
    def request_tokens_batch(self, items) -> List[_GrpcResult]:
        """``items``: [(flow_id, count, prioritized)] → aligned results."""
        return self._batch([(fid, cnt, prio, ()) for fid, cnt, prio in items])

    def request_param_tokens_batch(self, items) -> List[_GrpcResult]:
        """``items``: [(flow_id, count, params)] → aligned results."""
        return self._batch([(fid, cnt, False,
                             [str(p) for p in params])
                            for fid, cnt, params in items])

    def _batch(self, rows) -> List[_GrpcResult]:
        pb = self._pb
        req = pb.BatchTokenRequest(namespace=self.namespace)
        for fid, cnt, prio, params in rows:
            req.requests.add(flow_id=int(fid), acquire=int(cnt),
                             prioritized=bool(prio), params=params)
        try:
            resp = self._call(req, timeout=self.timeout_ms / 1000.0)
        except Exception:
            # deadline exceeded / unavailable / transport reset → FAIL per
            # item (fallbackToLocal applies; never fail open)
            return [_GrpcResult(STATUS_FAIL)] * len(rows)
        if len(resp.responses) != len(rows):
            return [_GrpcResult(STATUS_FAIL)] * len(rows)
        return [_GrpcResult(r.status, r.wait_ms, r.remaining)
                for r in resp.responses]

    # ------------------------------------------------------- single-call
    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False) -> _GrpcResult:
        return self.request_tokens_batch([(flow_id, count, prioritized)])[0]

    def request_param_token(self, flow_id: int, count: int,
                            params) -> _GrpcResult:
        return self.request_param_tokens_batch(
            [(flow_id, count, list(params))])[0]
