"""Cluster mode coordinator: turn the dashboard's mode flips into real
client/server lifecycles (reference ``ClusterStateManager`` +
``SentinelDefaultTokenServer`` embedded mode + ``DefaultClusterTokenClient``
wiring, SURVEY §2.3/§2.8.4: "any instance can become the token server").

Wire into the command plane::

    coord = ClusterCoordinator(sentinel)
    rt = start_transport(sentinel, ...)
    rt.cluster_state.add_observer(coord.on_mode_change)

Mode transitions:

- ``CLIENT`` (0): connect a :class:`ClusterTokenClient` to the configured
  server address and install it as the Sentinel's token service.
- ``SERVER`` (1): start an embedded :class:`ClusterTokenServer` (own
  engine) and install a loopback token service that talks to the local
  engine directly (the reference's ``EmbeddedClusterTokenServerProvider`` —
  the server instance serves its own requests in-process, no socket hop).
- ``NOT_STARTED`` (-1): stop whichever is running, uninstall.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from sentinel_tpu.core.logs import record_log

CLUSTER_NOT_STARTED = -1
CLUSTER_CLIENT = 0
CLUSTER_SERVER = 1


@dataclasses.dataclass
class EmbeddedTokenResult:
    status: int
    wait_ms: int = 0
    remaining: int = 0


class _EmbeddedTokenService:
    """Loopback TokenService over a local engine (no socket round-trip)."""

    def __init__(self, engine, clock=None):
        self.engine = engine
        self._clock = clock

    def _now(self) -> int:
        if self._clock is not None:
            return self._clock.now_ms()
        import time
        return int(time.time() * 1000)

    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False):
        status, wait, remaining = self.engine.request_tokens(
            [flow_id], [count], [prioritized], now_ms=self._now())[0]
        return EmbeddedTokenResult(status=status, wait_ms=wait,
                                   remaining=remaining)

    def request_param_token(self, flow_id: int, count: int, params):
        status, wait, remaining = self.engine.request_param_tokens(
            [flow_id], [count], [list(params)], now_ms=self._now())[0]
        return EmbeddedTokenResult(status=status, wait_ms=wait,
                                   remaining=remaining)

    # batched surface: the runtime's batch tier funnels a whole entry_batch
    # worth of token requests into ONE engine step instead of a device
    # round-trip per event (the reference has no analog — its token RPCs
    # are per-call — but the engine is batched end-to-end here)
    def request_tokens_batch(self, items):
        """items: [(flow_id, count, prioritized)] → aligned results."""
        res = self.engine.request_tokens(
            [i[0] for i in items], [i[1] for i in items],
            [bool(i[2]) for i in items], now_ms=self._now())
        return [EmbeddedTokenResult(status=s, wait_ms=w, remaining=r)
                for (s, w, r) in res]

    def request_param_tokens_batch(self, items):
        """items: [(flow_id, count, params)] → aligned results."""
        res = self.engine.request_param_tokens(
            [i[0] for i in items], [i[1] for i in items],
            [list(i[2]) for i in items], now_ms=self._now())
        return [EmbeddedTokenResult(status=s, wait_ms=w, remaining=r)
                for (s, w, r) in res]


class ClusterCoordinator:
    def __init__(self, sentinel, *, namespace: Optional[str] = None,
                 server_port: int = 0, n_shards: int = 1,
                 flows_per_shard: int = 64,
                 param_keys_per_shard: int = 1024, clock=None):
        self.sentinel = sentinel
        self.namespace = namespace or sentinel.cfg.app_name
        self.server_port = server_port
        self.n_shards = n_shards
        self.flows_per_shard = flows_per_shard
        # >0 so an assigned/embedded token server can serve cluster
        # hot-param rules too (reference embedded server always can)
        self.param_keys_per_shard = param_keys_per_shard
        self.clock = clock if clock is not None else sentinel.clock
        self._lock = threading.Lock()
        self.mode = CLUSTER_NOT_STARTED
        self.client = None
        self.server = None
        # client connection config (ClusterClientConfigManager)
        self.server_host = "127.0.0.1"
        self.server_port_client = 18730
        self.request_timeout_ms = 20

    # ---------------------------------------------------------------- wiring
    def bind(self, cluster_state, command_center=None) -> None:
        """Attach to a transport :class:`ClusterModeState`: mode flips and
        client-config pushes from the dashboard drive this coordinator, and
        ``getClusterMode`` reports the live token-server port. Passing the
        transport's ``CommandCenter`` also registers the ten
        ``cluster/server/*`` management commands (rules/config/metrics —
        reference ``sentinel-cluster-server-default`` handlers), resolved
        live against whichever engine/server this coordinator is running."""
        cluster_state.add_observer(self.on_mode_change)
        cluster_state.add_config_observer(
            lambda cfg: self.configure_client(
                cfg["serverHost"], int(cfg["serverPort"]),
                int(cfg["requestTimeout"])
                if "requestTimeout" in cfg else None))
        cluster_state.info_provider = self.info
        if command_center is not None:
            from sentinel_tpu.cluster.commands import (
                register_cluster_server_handlers,
            )
            register_cluster_server_handlers(command_center,
                                             coordinator=self)

    def info(self) -> dict:
        # lock-free snapshot: a mode change can hold the lock for seconds
        # (engine compile) and getClusterMode must not block behind it
        out = {"effectiveMode": self.mode}  # graftlint: disable=LOCK002 -- lock-free snapshot by design; a mode swap holds the lock for seconds and info() must not block
        server, client = self.server, self.client
        if server is not None:
            out["serverPort"] = server.port
        if client is not None:
            out["serverHost"] = self.server_host
            out["clientServerPort"] = self.server_port_client
        return out

    # ---------------------------------------------------------------- config
    def configure_client(self, host: str, port: int,
                         request_timeout_ms: Optional[int] = None) -> None:
        """``modifyClusterClientConfig``: on change, a running client
        reconnects to the new server (ServerChangeObserver)."""
        with self._lock:
            self.server_host = host
            self.server_port_client = port
            if request_timeout_ms is not None:
                self.request_timeout_ms = request_timeout_ms
            if self.mode == CLUSTER_CLIENT:
                self._stop_client_locked()
                try:
                    self._start_client_locked()
                except Exception as exc:
                    # same contract as on_mode_change: a failed restart
                    # leaves a retryable NOT_STARTED, never a phantom CLIENT
                    self.mode = CLUSTER_NOT_STARTED
                    record_log().warning(
                        "cluster client reconfigure failed: %r", exc)

    # ---------------------------------------------------------------- modes
    def on_mode_change(self, mode: int) -> None:
        with self._lock:
            if mode == self.mode:
                return
            self._stop_client_locked()
            self._stop_server_locked()
            # the old service is already gone: from here the effective mode
            # is NOT_STARTED until the new one starts, so a failed start
            # leaves a retryable state (not a stale mode that no-ops)
            self.mode = CLUSTER_NOT_STARTED
            try:
                if mode == CLUSTER_CLIENT:
                    self._start_client_locked()
                elif mode == CLUSTER_SERVER:
                    self._start_server_locked()
                else:
                    self.sentinel.set_token_service(None)
                self.mode = mode
            except Exception as exc:
                record_log().warning("cluster mode change failed: %r", exc)

    # ---------------------------------------------------------------- impl
    def _start_client_locked(self) -> None:
        from sentinel_tpu.cluster.client import ClusterTokenClient
        client = ClusterTokenClient(
            host=self.server_host, port=self.server_port_client,
            namespace=self.namespace,
            request_timeout_ms=self.request_timeout_ms)
        client.start()
        self.client = client
        self.sentinel.set_token_service(client)

    def _stop_client_locked(self) -> None:
        if self.client is not None:
            self.sentinel.set_token_service(None)
            try:
                self.client.stop()
            finally:
                self.client = None

    def _start_server_locked(self) -> None:
        from sentinel_tpu.cluster.server import ClusterTokenServer
        from sentinel_tpu.parallel.cluster import ClusterEngine, ClusterSpec
        engine = ClusterEngine(ClusterSpec(
            n_shards=self.n_shards, flows_per_shard=self.flows_per_shard,
            namespaces=4,
            param_keys_per_shard=self.param_keys_per_shard))
        server = ClusterTokenServer(engine, port=self.server_port,
                                    clock=self.clock)
        server.start()
        self.server = server
        # embedded mode: this instance's own cluster rules are served by
        # the in-process engine, no loopback socket
        self.sentinel.set_token_service(
            _EmbeddedTokenService(engine, clock=self.clock))

    def _stop_server_locked(self) -> None:
        if self.server is not None:
            self.sentinel.set_token_service(None)
            try:
                self.server.stop()
            finally:
                self.server = None

    def stop(self) -> None:
        with self._lock:
            self._stop_client_locked()
            self._stop_server_locked()
            self.mode = CLUSTER_NOT_STARTED
