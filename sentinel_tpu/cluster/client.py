"""Cluster token client SDK.

Reference: ``DefaultClusterTokenClient`` + ``NettyTransportClient`` +
``TokenClientPromiseHolder`` (sentinel-cluster-client-default, SURVEY §3.3):
requests are framed with a fresh xid, a promise is parked under that xid, and
the reader completes it when the matching response arrives; the transport
auto-reconnects every 2 s after a drop, and requests time out after 20 ms
(``ClusterConstants.DEFAULT_REQUEST_TIMEOUT``) → callers fall back to local
checks (``FlowRuleChecker.fallbackToLocalOrPass``).

This implementation is a plain blocking-socket client with a daemon reader
thread — it is the *app-side* SDK, deliberately free of jax/device state.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import socket
import struct
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from sentinel_tpu.cluster import codec
from sentinel_tpu.parallel.cluster import STATUS_FAIL

RECONNECT_DELAY_S = 2.0     # NettyTransportClient.RECONNECT_DELAY_MS
# Failed attempts back off exponentially from RECONNECT_DELAY_S up to this
# cap, with ±25% jitter so a restarted server isn't hit by a synchronized
# reconnect stampede from every client that dropped at the same instant.
RECONNECT_MAX_DELAY_S = 30.0
RECONNECT_JITTER = 0.25


@dataclasses.dataclass
class TokenResult:
    """cluster/TokenResult.java parity."""

    status: int
    remaining: int = 0
    wait_ms: int = 0
    token_id: int = 0

    @property
    def from_server(self) -> bool:
        return True


class ClusterTokenClient:
    """Blocking token client with xid-correlated in-flight requests."""

    def __init__(self, host: str = "127.0.0.1",
                 port: int = codec.DEFAULT_CLUSTER_SERVER_PORT,
                 *, namespace: str = "default",
                 request_timeout_ms: int = codec.DEFAULT_REQUEST_TIMEOUT_MS,
                 connect_timeout_s: float = 10.0,
                 auto_reconnect: bool = True):
        self.host = host
        self.port = port
        self.namespace = namespace
        self.request_timeout_ms = request_timeout_ms
        self.connect_timeout_s = connect_timeout_s
        self.auto_reconnect = auto_reconnect

        self._sock: Optional[socket.socket] = None
        self._xids = itertools.count(1)
        self._pending: Dict[int, Tuple[threading.Event, list]] = {}
        self._lock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._reconnector: Optional[threading.Thread] = None
        self._closed = False
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._connect()
        if self.auto_reconnect and self._reconnector is None:
            self._reconnector = threading.Thread(
                target=self._reconnect_loop, daemon=True,
                name="sentinel-cluster-client-reconnect")
            self._reconnector.start()

    def stop(self) -> None:
        self._closed = True
        self._stop.set()        # interrupt a reconnect backoff immediately
        self._teardown()

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _connect(self) -> None:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout_s)
        sock.settimeout(None)
        self._sock = sock
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="sentinel-cluster-client-reader")
        self._reader.start()
        # register namespace (TokenClientHandler sends PING on activation)
        self.ping()

    def _teardown(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        with self._lock:
            for ev, slot in self._pending.values():
                slot.append(None)
                ev.set()
            self._pending.clear()

    def _reconnect_loop(self) -> None:
        # Interruptible, jittered exponential backoff: a healthy (or
        # freshly re-established) connection keeps the probe cadence at
        # the reference's RECONNECT_DELAY_S; consecutive failed attempts
        # double the delay up to RECONNECT_MAX_DELAY_S. Event.wait (not
        # time.sleep) so stop() tears the loop down immediately instead
        # of leaving a sleeping daemon holding the old socket's state.
        delay = RECONNECT_DELAY_S
        while not self._closed:
            jittered = delay * random.uniform(1 - RECONNECT_JITTER,
                                              1 + RECONNECT_JITTER)
            if self._stop.wait(timeout=jittered):
                break
            if self._sock is None and not self._closed:
                try:
                    self._connect()
                    delay = RECONNECT_DELAY_S
                except OSError:
                    delay = min(delay * 2, RECONNECT_MAX_DELAY_S)
            else:
                delay = RECONNECT_DELAY_S

    def _read_loop(self) -> None:
        assembler = codec.FrameAssembler()
        sock = self._sock
        try:
            while sock is self._sock and sock is not None:
                data = sock.recv(4096)
                if not data:
                    break
                for frame in assembler.feed(data):
                    resp = codec.decode_response(frame)
                    if resp is None:
                        continue
                    with self._lock:
                        entry = self._pending.pop(resp.xid, None)
                    if entry is not None:
                        ev, slot = entry
                        slot.append(resp)
                        ev.set()
        except (OSError, ValueError):
            pass
        finally:
            if sock is self._sock:
                self._teardown()

    # ------------------------------------------------------------------
    def _roundtrip(self, req: codec.Request,
                   timeout_ms: Optional[int] = None) -> Optional[codec.Response]:
        sock = self._sock
        if sock is None:
            return None
        ev = threading.Event()
        slot: list = []
        with self._lock:
            self._pending[req.xid] = (ev, slot)
        try:
            sock.sendall(codec.encode_request(req))
        except OSError:
            with self._lock:
                self._pending.pop(req.xid, None)
            self._teardown()
            return None
        budget = (timeout_ms if timeout_ms is not None
                  else self.request_timeout_ms) / 1000.0
        if not ev.wait(timeout=budget):
            with self._lock:
                self._pending.pop(req.xid, None)
            return None
        return slot[0] if slot and slot[0] is not None else None

    # ------------------------------------------------------------------
    # TokenService surface (cluster/TokenService.java)
    # ------------------------------------------------------------------

    def ping(self) -> Optional[int]:
        resp = self._roundtrip(codec.Request(
            next(self._xids), codec.MSG_TYPE_PING, self.namespace),
            timeout_ms=2000)
        return int(resp.data) if resp is not None else None

    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False) -> TokenResult:
        resp = self._roundtrip(codec.Request(
            next(self._xids), codec.MSG_TYPE_FLOW,
            (flow_id, count, prioritized)))
        if resp is None:
            return TokenResult(STATUS_FAIL)
        remaining, wait_ms = resp.data or (0, 0)
        return TokenResult(resp.status, remaining=remaining, wait_ms=wait_ms)

    def request_param_token(self, flow_id: int, count: int,
                            params: Sequence[object]) -> TokenResult:
        resp = self._roundtrip(codec.Request(
            next(self._xids), codec.MSG_TYPE_PARAM_FLOW,
            (flow_id, count, list(params))))
        if resp is None:
            return TokenResult(STATUS_FAIL)
        remaining, wait_ms = resp.data or (0, 0)
        return TokenResult(resp.status, remaining=remaining, wait_ms=wait_ms)

    # ------------------------------------------------------------------
    # Pipelined batch surface (the xid correlation already supports N
    # concurrent in-flight requests — the reference runs N caller threads
    # through one channel the same way; here one caller writes N frames
    # back-to-back and collects the responses under one shared deadline,
    # so a batch pays ~one RTT instead of N)
    # ------------------------------------------------------------------

    def _send_pipelined(self, reqs):
        """Register + write many frames in one ``sendall``; → [(xid, ev,
        slot)] or None when disconnected."""
        sock = self._sock
        if sock is None:
            return None
        entries = []
        with self._lock:
            for req in reqs:
                ev = threading.Event()
                slot: list = []
                self._pending[req.xid] = (ev, slot)
                entries.append((req.xid, ev, slot))
        try:
            sock.sendall(b"".join(codec.encode_request(r) for r in reqs))
        except OSError:
            with self._lock:
                for xid, _, _ in entries:
                    self._pending.pop(xid, None)
            self._teardown()
            return None
        return entries

    def _collect_pipelined(self, entries, timeout_ms: Optional[int]):
        """Collect responses in send order under a ROLLING deadline: every
        observed response extends the allowance by one request timeout, so
        a healthy server streaming responses never starves late items
        (preserving the reference's per-request 20 ms contract under
        pipelining), while a hung-but-connected server exhausts ONE budget
        and the remainder of the batch fails immediately — not N stacked
        timeouts."""
        budget_s = (timeout_ms if timeout_ms is not None
                    else self.request_timeout_ms) / 1000.0
        deadline = time.monotonic() + budget_s
        out = []
        for xid, ev, slot in entries:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ev.wait(timeout=remaining):
                with self._lock:
                    self._pending.pop(xid, None)
                out.append(None)
                continue
            deadline = time.monotonic() + budget_s     # progress → extend
            out.append(slot[0] if slot and slot[0] is not None else None)
        return out

    def _batch_roundtrip(self, reqs, n: int, timeout_ms: Optional[int]):
        entries = self._send_pipelined(reqs)
        if entries is None:
            return [TokenResult(STATUS_FAIL)] * n
        out = []
        for resp in self._collect_pipelined(entries, timeout_ms):
            if resp is None:
                out.append(TokenResult(STATUS_FAIL))
            else:
                remaining, wait_ms = resp.data or (0, 0)
                out.append(TokenResult(resp.status, remaining=remaining,
                                       wait_ms=wait_ms))
        return out

    def request_tokens_batch(self, items,
                             timeout_ms: Optional[int] = None):
        """``items``: [(flow_id, count, prioritized)] → aligned
        :class:`TokenResult` list; transport failure → FAIL per item (the
        caller's fallbackToLocal semantics apply per rule)."""
        reqs = [codec.Request(next(self._xids), codec.MSG_TYPE_FLOW,
                              (int(fid), int(cnt), bool(prio)))
                for fid, cnt, prio in items]
        return self._batch_roundtrip(reqs, len(items), timeout_ms)

    def request_param_tokens_batch(self, items,
                                   timeout_ms: Optional[int] = None):
        """``items``: [(flow_id, count, params)] → aligned results."""
        reqs = [codec.Request(next(self._xids), codec.MSG_TYPE_PARAM_FLOW,
                              (int(fid), int(cnt), list(params)))
                for fid, cnt, params in items]
        return self._batch_roundtrip(reqs, len(items), timeout_ms)

    def acquire_concurrent_token(self, flow_id: int,
                                 count: int = 1) -> TokenResult:
        resp = self._roundtrip(codec.Request(
            next(self._xids), codec.MSG_TYPE_CONCURRENT_FLOW_ACQUIRE,
            (flow_id, count, False)))
        if resp is None:
            return TokenResult(STATUS_FAIL)
        return TokenResult(resp.status, token_id=int(resp.data or 0))

    def release_concurrent_token(self, token_id: int) -> TokenResult:
        resp = self._roundtrip(codec.Request(
            next(self._xids), codec.MSG_TYPE_CONCURRENT_FLOW_RELEASE,
            token_id))
        if resp is None:
            return TokenResult(STATUS_FAIL)
        return TokenResult(resp.status)
