"""Native (C++) host-path components.

The device compute path is JAX/XLA; this package holds the host-side pieces
where Python-level overhead caps throughput — currently the string-interning
registry feeding resource names into the batched device step (SURVEY §7 hard
part 5). Everything here has a pure-Python fallback: the native library is
compiled on demand with g++ (no pip installs) and cached next to its source;
``SENTINEL_TPU_NATIVE=0`` disables it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

_SRC = Path(__file__).parent / "src" / "registry.cpp"
_LIB = Path(__file__).parent / "src" / "_sentinel_native.so"

_lib_handle = None
_lib_lock = threading.Lock()


def _build() -> Optional[Path]:
    """Compile the shared library if missing/stale; None on failure.
    Compiles to a per-pid temp path and renames into place so concurrent
    processes never load a half-written ELF."""
    try:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return _LIB
        tmp = _LIB.with_suffix(f".{os.getpid()}.tmp.so")
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             str(_SRC), "-o", str(tmp)],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)          # atomic on POSIX
        return _LIB
    except (OSError, subprocess.SubprocessError):
        return None


def load_native():
    """The ctypes library, or None when disabled/unbuildable."""
    global _lib_handle
    if os.environ.get("SENTINEL_TPU_NATIVE", "1") == "0":
        return None
    with _lib_lock:
        if _lib_handle is not None:
            return None if _lib_handle is False else _lib_handle
        path = _build()
        if path is None:
            _lib_handle = False        # cache the failure: no retry storms
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            _lib_handle = False
            return None
        lib.str_new.restype = ctypes.c_void_p
        lib.str_new.argtypes = [ctypes.c_int32]
        lib.str_free.argtypes = [ctypes.c_void_p]
        for fn in (lib.str_get_or_create, lib.str_lookup, lib.str_pin):
            fn.restype = ctypes.c_int32
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
        lib.str_unpin.restype = None
        lib.str_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int32]
        lib.str_name_of.restype = ctypes.c_int32
        lib.str_name_of.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                    ctypes.c_char_p, ctypes.c_int32]
        lib.str_len.restype = ctypes.c_int32
        lib.str_len.argtypes = [ctypes.c_void_p]
        lib.str_drain.restype = ctypes.c_int32
        lib.str_drain.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int32),
                                  ctypes.c_int32]
        lib.str_get_or_create_batch.restype = ctypes.c_int32
        lib.str_get_or_create_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32)]
        lib.str_live_ids.restype = ctypes.c_int32
        lib.str_live_ids.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int32),
                                     ctypes.c_int32]
        lib.str_snapshot.restype = ctypes.c_int32
        lib.str_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32]
        lib.str_get_or_create_batch2.restype = ctypes.c_int32
        lib.str_get_or_create_batch2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8)]
        lib.i64_get_or_create_batch.restype = ctypes.c_int32
        lib.i64_get_or_create_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8)]
        for fn in (lib.str_pin_rows, lib.str_unpin_rows):
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.str_pin_total.restype = ctypes.c_int64
        lib.str_pin_total.argtypes = [ctypes.c_void_p]
        _lib_handle = lib
        return lib


class NativeRegistry:
    """Drop-in for :class:`sentinel_tpu.core.registry.Registry` backed by the
    C++ table. Same semantics: dense ids, LRU eviction of unpinned rows on
    overflow, pending-evicted drain, pinning."""

    def __init__(self, capacity: int, reserved=()):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        reserved = tuple(reserved)
        if capacity < 1 + len(reserved):
            raise ValueError("capacity too small")
        self._lib = lib
        self._capacity = capacity
        self._h = ctypes.c_void_p(lib.str_new(capacity))
        if not self._h:
            raise MemoryError("str_new failed")
        for name in reserved:
            self.pin(name)

    # -- lifecycle ---------------------------------------------------------
    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.str_free(h)

    @property
    def capacity(self) -> int:
        return self._capacity

    # -- core --------------------------------------------------------------
    def get_or_create(self, name: str) -> int:
        b = name.encode("utf-8")
        rid = self._lib.str_get_or_create(self._h, b, len(b))
        if rid == -2:
            raise RuntimeError("registry full and all rows pinned")
        return rid

    def get_or_create_batch(self, names) -> np.ndarray:
        """Vector path: one lock + one FFI call for the whole batch.
        Batches repeat few distinct names (per-resource serving loops often
        send ONE name 4k times), so dedup first when it pays — dict hashing
        a name is ~30× cheaper than encoding + marshalling it."""
        n = len(names)
        if n > 64:
            # all-identical batch (per-resource serving loops): ONE intern,
            # no dict pass — names.count is a C-speed scan
            first = names[0]
            if isinstance(names, list) and names.count(first) == n:
                row = self.get_or_create(first)
                return np.full(n, row, np.int32)
            pos: dict = {}
            for s in names:
                if s not in pos:
                    pos[s] = len(pos)
            if len(pos) * 2 < n:
                rows_u = self._intern_encoded(list(pos))
                return rows_u[np.fromiter((pos[s] for s in names),
                                          np.int32, count=n)]
        return self._intern_encoded(names)

    def _intern_encoded(self, names) -> np.ndarray:
        enc = [n.encode("utf-8") for n in names]
        offsets = np.zeros(len(enc) + 1, np.int32)
        np.cumsum([len(b) for b in enc], out=offsets[1:])
        data = b"".join(enc)
        out = np.empty(len(enc), np.int32)
        self._lib.str_get_or_create_batch(
            self._h, data,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(enc),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if (out == -2).any():
            raise RuntimeError("registry full and all rows pinned")
        return out

    def lookup(self, name: str) -> Optional[int]:
        b = name.encode("utf-8")
        rid = self._lib.str_lookup(self._h, b, len(b))
        return None if rid < 0 else rid

    def name_of(self, rid: int) -> Optional[str]:
        size = 4096
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.str_name_of(self._h, rid, buf, size)
            if n < 0:
                return None
            if n <= size:              # full name fit (no mid-codepoint cut)
                return buf.raw[:n].decode("utf-8")
            size = n

    def pin(self, name: str) -> int:
        b = name.encode("utf-8")
        rid = self._lib.str_pin(self._h, b, len(b))
        if rid == -2:
            raise RuntimeError("registry full and all rows pinned")
        return rid

    def unpin(self, name: str) -> None:
        b = name.encode("utf-8")
        self._lib.str_unpin(self._h, b, len(b))

    def drain_evicted(self) -> List[int]:
        # the queue can exceed capacity (a row evicted repeatedly between
        # drains) — keep pulling until the C side reports it empty
        out = np.empty(max(self._capacity, 64), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        result: List[int] = []
        while True:
            n = self._lib.str_drain(self._h, ptr, len(out))
            result.extend(int(x) for x in out[:n])
            if n < len(out):
                return result

    def items(self) -> List[Tuple[str, int]]:
        # one C-side lock acquisition: ids and names are a consistent pair
        # even while another thread is evicting/interning
        ids = np.empty(self._capacity, np.int32)
        lens = np.empty(self._capacity, np.int32)
        buflen = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(buflen)
            n = self._lib.str_snapshot(
                self._h,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                self._capacity, buf, buflen)
            if n >= 0:
                break
            buflen = -n
        out = []
        off = 0
        for i in range(n):
            ln = int(lens[i])
            out.append((buf.raw[off:off + ln].decode("utf-8"),
                        int(ids[i])))
            off += ln
        return out

    def __len__(self) -> int:
        return int(self._lib.str_len(self._h))


def native_available() -> bool:
    return load_native() is not None
