// Native string-interning registry: name -> dense row id with LRU eviction,
// pinning, and an evicted-row queue — behavior-identical to the Python
// Registry in sentinel_tpu/core/registry.py (which mirrors the reference's
// copy-on-write name maps, CtSph.java:202-226, minus the silent 6,000-chain
// cap). This is the one host-side hot path worth native code (SURVEY §7
// hard part 5: name->id at tens of millions/sec feeds the batched device
// step); everything device-side stays JAX/XLA.
//
// C ABI only (loaded via ctypes): no CPython API, so the GIL is naturally
// released for the duration of every call made through ctypes.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC registry.cpp -o _sentinel_native.so

#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>
#include <vector>

namespace {

// FNV-1a 64
static inline uint64_t fnv1a(const char* s, int len) {
    uint64_t h = 1469598103934665603ull;
    for (int i = 0; i < len; ++i) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ull;
    }
    return h;
}

struct Entry {
    char*    name = nullptr;     // owned copy, NUL-terminated
    int      len = 0;
    int32_t  id = -1;            // row id
    // intrusive LRU list over *occupied* entries (most recent at tail)
    int32_t  prev = -1;          // index into slots_, -1 = none
    int32_t  next = -1;
    bool     pinned = false;     // string-API pin (rule resources: sticky)
    uint32_t pin_count = 0;      // row-API counted pins (in-flight entries)
};

struct Table {
    std::mutex mu;
    int32_t capacity;            // max live names (== row id space)
    std::vector<int32_t> buckets;   // open addressing: slot index or -1
    std::vector<Entry> slots;       // slot i owns row id i (dense!)
    std::vector<int32_t> evicted;   // pending drain
    int32_t next_id = 0;
    int32_t lru_head = -1;          // least recently used
    int32_t lru_tail = -1;          // most recently used
    int32_t live = 0;

    explicit Table(int32_t cap)
        : capacity(cap), slots(cap) {
        // bucket table sized to >= 2x capacity, power of two
        size_t n = 8;
        while (n < (size_t)cap * 2) n <<= 1;
        buckets.assign(n, -1);
    }
    ~Table() {
        for (auto& e : slots) delete[] e.name;
    }

    inline size_t mask() const { return buckets.size() - 1; }

    // --- LRU list ---------------------------------------------------------
    void lru_unlink(int32_t i) {
        Entry& e = slots[i];
        if (e.prev >= 0) slots[e.prev].next = e.next; else lru_head = e.next;
        if (e.next >= 0) slots[e.next].prev = e.prev; else lru_tail = e.prev;
        e.prev = e.next = -1;
    }
    void lru_push_tail(int32_t i) {
        Entry& e = slots[i];
        e.prev = lru_tail;
        e.next = -1;
        if (lru_tail >= 0) slots[lru_tail].next = i; else lru_head = i;
        lru_tail = i;
    }

    // --- buckets ----------------------------------------------------------
    // find the bucket holding `name`, or the first empty bucket.
    size_t probe(const char* name, int len, bool* found) const {
        size_t i = fnv1a(name, len) & mask();
        for (;;) {
            int32_t s = buckets[i];
            if (s < 0) { *found = false; return i; }
            const Entry& e = slots[s];
            if (e.len == len && std::memcmp(e.name, name, len) == 0) {
                *found = true;
                return i;
            }
            i = (i + 1) & mask();
        }
    }
    void bucket_erase(const char* name, int len) {
        // tombstone-free deletion for linear probing (backward shift)
        bool found;
        size_t i = probe(name, len, &found);
        if (!found) return;
        size_t j = i;
        for (;;) {
            j = (j + 1) & mask();
            int32_t s = buckets[j];
            if (s < 0) break;
            size_t home = fnv1a(slots[s].name, slots[s].len) & mask();
            // can slot j's entry be moved into the hole at i?
            bool wraps = (j < home);
            bool between = wraps ? (i >= home || i < j) : (i >= home && i < j);
            if (between) {
                buckets[i] = s;
                i = j;
            }
        }
        buckets[i] = -1;
    }

    // --- core ops ---------------------------------------------------------
    int32_t evict_locked() {
        for (int32_t i = lru_head; i >= 0; i = slots[i].next) {
            if (!slots[i].pinned && slots[i].pin_count == 0) {
                Entry& e = slots[i];
                bucket_erase(e.name, e.len);
                lru_unlink(i);
                delete[] e.name;
                e.name = nullptr;
                e.len = 0;
                --live;
                evicted.push_back(e.id);
                return i;                      // slot index == row id
            }
        }
        return -2;                             // all pinned
    }

    // touch_on_hit: only the plain get_or_create path refreshes LRU order on
    // a hit — lookup() and pin() leave order untouched, exactly like the
    // Python Registry (move_to_end only in get_or_create)
    int32_t get_or_create(const char* name, int len, bool create, bool pin,
                          bool touch_on_hit) {
        bool found;
        size_t b = probe(name, len, &found);
        if (found) {
            int32_t s = buckets[b];
            if (touch_on_hit) {
                lru_unlink(s);
                lru_push_tail(s);
            }
            if (pin) slots[s].pinned = true;
            return slots[s].id;
        }
        if (!create) return -1;
        int32_t slot;
        if (next_id < capacity) {
            slot = next_id++;
        } else {
            slot = evict_locked();
            if (slot < 0) return -2;
            // eviction may have shifted buckets: re-probe for our insert slot
            b = probe(name, len, &found);
        }
        Entry& e = slots[slot];
        e.name = new char[len + 1];
        std::memcpy(e.name, name, len);
        e.name[len] = '\0';
        e.len = len;
        e.id = slot;
        e.pinned = pin;
        // pin_count deliberately NOT reset: counted row pins are
        // independent of key liveness (a pin taken on a row protects its
        // next occupant too — exactly the Python registry's _pins dict)
        buckets[b] = slot;
        lru_push_tail(slot);
        ++live;
        return slot;
    }

    // get_or_create that also reports creation (param-key overrides apply
    // only when the key is newly interned)
    int32_t get_or_create2(const char* name, int len, uint8_t* created) {
        bool found;
        probe(name, len, &found);
        *created = found ? 0 : 1;
        return get_or_create(name, len, /*create=*/true, /*pin=*/false,
                             /*touch_on_hit=*/true);
    }
};

}  // namespace

extern "C" {

void* str_new(int32_t capacity) {
    if (capacity < 1) return nullptr;
    return new (std::nothrow) Table(capacity);
}

void str_free(void* h) { delete static_cast<Table*>(h); }

int32_t str_get_or_create(void* h, const char* name, int32_t len) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    return t->get_or_create(name, len, /*create=*/true, /*pin=*/false,
                            /*touch_on_hit=*/true);
}

int32_t str_lookup(void* h, const char* name, int32_t len) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    return t->get_or_create(name, len, /*create=*/false, /*pin=*/false,
                            /*touch_on_hit=*/false);
}

int32_t str_pin(void* h, const char* name, int32_t len) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    return t->get_or_create(name, len, /*create=*/true, /*pin=*/true,
                            /*touch_on_hit=*/false);
}

void str_unpin(void* h, const char* name, int32_t len) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    bool found;
    size_t b = t->probe(name, len, &found);
    if (found) t->slots[t->buckets[b]].pinned = false;
}

// touch-free read of one id's name; returns length or -1; copies at most
// buflen bytes (no NUL) into buf.
int32_t str_name_of(void* h, int32_t id, char* buf, int32_t buflen) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    if (id < 0 || id >= t->capacity) return -1;
    const Entry& e = t->slots[id];
    if (e.name == nullptr) return -1;
    int32_t n = e.len < buflen ? e.len : buflen;
    std::memcpy(buf, e.name, n);
    return e.len;
}

int32_t str_len(void* h) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    return t->live;
}

// drain evicted ids into out (up to cap); returns count written; remaining
// stay queued.
int32_t str_drain(void* h, int32_t* out, int32_t cap) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    int32_t n = (int32_t)t->evicted.size();
    if (n > cap) n = cap;
    std::memcpy(out, t->evicted.data(), n * sizeof(int32_t));
    t->evicted.erase(t->evicted.begin(), t->evicted.begin() + n);
    return n;
}

// batch get_or_create: names concatenated in `data`, offsets[n+1] bounds.
// Returns number processed (== n unless a row allocation failed, where the
// failing and remaining entries get id -2 and processing continues).
int32_t str_get_or_create_batch(void* h, const char* data,
                                const int32_t* offsets, int32_t n,
                                int32_t* out) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    for (int32_t i = 0; i < n; ++i) {
        out[i] = t->get_or_create(data + offsets[i],
                                  offsets[i + 1] - offsets[i],
                                  /*create=*/true, /*pin=*/false,
                                  /*touch_on_hit=*/true);
    }
    return n;
}

// ---- param-key extensions (hot-key table: composite keys, counted row
// pins, created flags — the ParamKeyRegistry analog; see
// rules/param_flow.py NativeParamKeyRegistry for the key encodings) ----

// batch get_or_create with created flags (concatenated keys like
// str_get_or_create_batch).
int32_t str_get_or_create_batch2(void* h, const char* data,
                                 const int32_t* offsets, int32_t n,
                                 int32_t* out, uint8_t* created) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    for (int32_t i = 0; i < n; ++i) {
        out[i] = t->get_or_create2(data + offsets[i],
                                   offsets[i + 1] - offsets[i],
                                   created + i);
    }
    return n;
}

// int-key fast path: each packed key is slot * 2^32 + (value + 2^31)
// (the vector resolution path's combine-key). The canonical key bytes
// [slot le4]['i'][value le8] are produced HERE, so Python never encodes
// per-key — one FFI call per batch of distinct keys.
int32_t i64_get_or_create_batch(void* h, const int64_t* packed, int32_t n,
                                int32_t* out, uint8_t* created) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    char key[13];
    for (int32_t i = 0; i < n; ++i) {
        int64_t p = packed[i];
        int32_t slot = (int32_t)(p >> 32);
        int64_t value = (int64_t)(p & 0xffffffffll) - (1ll << 31);
        // Explicit little-endian byte writes: Python's string-path
        // encoder pins '<i'/'<q', so a host-endian memcpy on a
        // big-endian machine would intern the same logical key twice.
        uint32_t us = (uint32_t)slot;
        uint64_t uv = (uint64_t)value;
        for (int b = 0; b < 4; ++b) key[b] = (char)((us >> (8 * b)) & 0xff);
        key[4] = 'i';
        for (int b = 0; b < 8; ++b)
            key[5 + b] = (char)((uv >> (8 * b)) & 0xff);
        out[i] = t->get_or_create2(key, 13, created + i);
    }
    return n;
}

// total live counted row pins (observability / test introspection)
int64_t str_pin_total(void* h) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    int64_t n = 0;
    for (const auto& e : t->slots) n += e.pin_count;
    return n;
}

// counted row pins: one increment/decrement per occurrence in rows[]
// (duplicates intended — the caller passes raw in-flight pair rows).
void str_pin_rows(void* h, const int32_t* rows, int32_t n) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    for (int32_t i = 0; i < n; ++i) {
        int32_t r = rows[i];
        if (r >= 0 && r < t->capacity) ++t->slots[r].pin_count;
    }
}

void str_unpin_rows(void* h, const int32_t* rows, int32_t n) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    for (int32_t i = 0; i < n; ++i) {
        int32_t r = rows[i];
        if (r >= 0 && r < t->capacity && t->slots[r].pin_count > 0)
            --t->slots[r].pin_count;
    }
}

// iterate live (name, id) pairs: copies ids of live slots into out_ids,
// returns live count (names retrievable via str_name_of).
int32_t str_live_ids(void* h, int32_t* out_ids, int32_t cap) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    int32_t n = 0;
    // LRU order (oldest first) to mirror the Python OrderedDict iteration
    for (int32_t i = t->lru_head; i >= 0 && n < cap; i = t->slots[i].next)
        out_ids[n++] = t->slots[i].id;
    return n;
}

// Atomic (id, name) snapshot under ONE lock acquisition (items() must not
// pair ids with names across eviction windows). Writes up to `cap` live
// entries in LRU order (oldest first): ids[i], lens[i], names concatenated
// into buf. Returns the live count, or -(bytes needed) when buf is too
// small (caller retries with a bigger buffer).
int32_t str_snapshot(void* h, int32_t* ids, int32_t* lens, int32_t cap,
                     char* buf, int32_t buflen) {
    Table* t = static_cast<Table*>(h);
    std::lock_guard<std::mutex> g(t->mu);
    int64_t need = 0;
    for (int32_t i = t->lru_head; i >= 0; i = t->slots[i].next)
        need += t->slots[i].len;
    if (need > buflen) return (int32_t)-need;
    int32_t n = 0;
    int32_t off = 0;
    for (int32_t i = t->lru_head; i >= 0 && n < cap; i = t->slots[i].next) {
        const Entry& e = t->slots[i];
        ids[n] = e.id;
        lens[n] = e.len;
        std::memcpy(buf + off, e.name, e.len);
        off += e.len;
        ++n;
    }
    return n;
}

}  // extern "C"
