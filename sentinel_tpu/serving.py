"""Depth-k dispatch pipelining over the runtime's nowait tier.

The synchronous serving loop — ``entry_batch_nowait(...).result()`` per
step — pays the full host dispatch cost (~2.4 ms measured floor,
BENCH_r05) on every batch: the host prepares batch N, dispatches it,
then idles until N's verdicts materialize before touching N+1.
:class:`DispatchPipeline` keeps up to ``depth`` batches in flight:
``submit`` dispatches batch N+1 while N still runs on device and
settles N-k only when the window is full, so the host's prep/dispatch
cost overlaps device execution instead of adding to it.

Ordering semantics are UNCHANGED from the sequential loop: the runtime
advances engine state at dispatch time under its own lock (submission
order == state order), and the pipeline settles handles strictly in
submission order — ``PipelinedVerdicts.result()`` for batch N first
settles every older in-flight batch, so deferred host bookkeeping
(blocked-pin release, block log, breaker diffs) also lands in dispatch
order. ``tests/test_dispatch_pipeline.py`` pins
``pipelined(depth=k) == sequential`` bit-parity.

Self-telemetry (obs/): ``pipeline.enqueue`` / ``pipeline.settle`` spans
on sampled batches, ``pipeline.depth`` (sum of in-flight counts at each
enqueue — divide by enqueues for the achieved average depth),
``pipeline.stall`` (submits that had to settle the oldest batch first)
and ``pipeline.meshed_dispatch`` (submits whose backing runtime is
row-sharded over a mesh) counters. Knob: ``SENTINEL_PIPELINE_DEPTH``
(default 2).
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.runtime import (   # noqa: F401 - re-exported knob
    PIPELINE_DEPTH_ENV, PendingVerdicts, Sentinel, pipeline_depth,
)

_MISSING = object()


class PipelinedVerdicts:
    """Ticket for one submitted batch: ``result()`` settles every older
    in-flight batch first (strict in-order settle), then memoizes this
    batch's :class:`~sentinel_tpu.engine.pipeline.Verdicts`. Safe to call
    out of submission order and more than once."""

    __slots__ = ("_pipe", "_seq", "_done", "_res")

    def __init__(self, pipe: "DispatchPipeline", seq: int):
        self._pipe = pipe
        self._seq = seq
        self._done = False
        self._res = None

    @property
    def seq(self) -> int:
        return self._seq

    def result(self):
        if not self._done:
            self._res = self._pipe._settle_through(self._seq)
            self._done = True
            self._pipe = None
        return self._res


class DispatchPipeline:
    """Depth-k dispatch window over one :class:`Sentinel`.

    Typical serving loop (rows pre-interned once via
    ``Sentinel.intern_resources``)::

        pipe = DispatchPipeline(sentinel)          # depth from env, or pass
        tickets = collections.deque()
        for step_rows in traffic:
            tickets.append(pipe.submit(step_rows))
            if len(tickets) > pipe.depth:
                verdicts = tickets.popleft().result()
                ...
        pipe.flush()

    ``depth=1`` degenerates to the synchronous loop (every submit settles
    the previous batch). The pipeline serializes submits under its own
    lock; use one pipeline per dispatcher thread.
    """

    def __init__(self, sentinel: Sentinel, depth: Optional[int] = None,
                 on_settle=None):
        self._s = sentinel
        # row-sharded runtime underneath: each submit also lands a
        # pipeline.meshed_dispatch counter so the scrape can attribute
        # pipeline traffic to the mesh path without reading the runtime
        self._meshed = sentinel.mesh is not None
        if depth is None:
            # default depth: the engine's tuned-config resolution
            # (round 11 — SENTINEL_TUNED_CONFIG, env-unset knobs only)
            # falls back to the SENTINEL_PIPELINE_DEPTH env clamp
            tuned = getattr(sentinel, "_tuned", None) or {}
            depth = tuned.get(PIPELINE_DEPTH_ENV, pipeline_depth())
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        # (seq, PendingVerdicts) in submission order
        self._inflight: "collections.deque" = collections.deque()
        # seq → settled Verdicts awaiting its ticket's result()
        self._results: dict = {}
        self._next_seq = 0
        # on_settle(seq, verdicts): fired after EVERY settle — stall,
        # result() drain, or flush — so an overlay (the frontend ingest
        # batcher) learns a batch landed at the earliest possible moment,
        # whichever call settled it. Called with the pipeline lock held:
        # keep it quick, and never call back into this pipeline from it
        # (the frontend hands off via loop.call_soon_threadsafe).
        self._on_settle = on_settle

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(self, resources, **entry_kwargs) -> PipelinedVerdicts:
        """Dispatch one entry batch through
        :meth:`Sentinel.entry_batch_nowait` (all its kwargs pass
        through: origins, acquire, prioritized, args_list, ...).
        ``trace_id`` threads a caller-minted trace (the frontend's batch
        trace) through the pipeline AND the runtime dispatch, so the
        whole lifecycle records under one id."""
        n = len(resources)
        trace_id = entry_kwargs.get("trace_id", 0)
        return self._submit(
            lambda: self._s.entry_batch_nowait(resources, **entry_kwargs),
            n, trace_id=trace_id)

    def submit_raw(self, *args, **kwargs) -> PipelinedVerdicts:
        """Dispatch through :meth:`Sentinel.decide_raw_nowait` (the
        registry-free tier: pre-resolved rows/ids in, verdicts out)."""
        n = args[0].shape[0] if args else 0
        return self._submit(
            lambda: self._s.decide_raw_nowait(*args, **kwargs), n,
            trace_id=kwargs.get("trace_id", 0))

    def submit_fused(self, *args, **kwargs) -> PipelinedVerdicts:
        """Dispatch through :meth:`Sentinel.decide_and_exit_raw_nowait`:
        this step's decides and the previous step's completions in ONE
        device program (see its docstring for the applicability scope)."""
        n = args[0].shape[0] if args else 0
        return self._submit(
            lambda: self._s.decide_and_exit_raw_nowait(*args, **kwargs), n,
            trace_id=kwargs.get("trace_id", 0))

    def _submit(self, dispatch, n: int,
                trace_id: int = 0) -> PipelinedVerdicts:
        obs = self._s.obs
        obs_on = obs.enabled
        tr = (trace_id or obs.spans.maybe_trace()) if obs_on else 0
        t0 = obs.spans.now_ns() if tr else 0
        with self._lock:
            # make room BEFORE dispatching: settling the oldest here (a
            # stall) keeps at most `depth` batches in flight and bounds
            # how long deferred bookkeeping can wait
            while len(self._inflight) >= self.depth:
                if obs_on:
                    obs.counters.add(obs_keys.PIPE_STALL)
                self._settle_oldest_locked()
            handle = dispatch()
            seq = self._next_seq
            self._next_seq += 1
            # the batch's trace id rides the in-flight entry so the
            # settle span lands on the SAME chain as the enqueue span
            self._inflight.append((seq, handle, tr))
            if obs_on:
                obs.counters.add(obs_keys.PIPE_DEPTH, len(self._inflight))
                if self._meshed:
                    obs.counters.add(obs_keys.PIPE_MESHED)
        if tr:
            obs.spans.record(tr, "pipeline.enqueue", t0, obs.spans.now_ns(),
                             n=n, note=f"seq={seq}")
        return PipelinedVerdicts(self, seq)

    # ------------------------------------------------------------------
    # settlement
    # ------------------------------------------------------------------

    def _settle_oldest_locked(self) -> None:
        seq, handle, tr = self._inflight.popleft()
        obs = self._s.obs
        if not obs.enabled:
            tr = 0
        t0 = obs.spans.now_ns() if tr else 0
        self._results[seq] = handle.result()
        if tr:
            obs.spans.record(tr, "pipeline.settle", t0, obs.spans.now_ns(),
                             note=f"seq={seq}")
        if self._on_settle is not None:
            self._on_settle(seq, self._results[seq])

    def _settle_through(self, seq: int):
        with self._lock:
            res = self._results.pop(seq, _MISSING)
            if res is not _MISSING:
                return res
            while self._inflight and self._inflight[0][0] <= seq:
                self._settle_oldest_locked()
            res = self._results.pop(seq, _MISSING)
        if res is _MISSING:
            raise KeyError(f"unknown or already-consumed batch seq {seq}")
        return res

    @property
    def in_flight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def flush(self) -> None:
        """Settle every in-flight batch (their verdicts stay claimable
        via the corresponding tickets)."""
        with self._lock:
            while self._inflight:
                self._settle_oldest_locked()

    def __enter__(self) -> "DispatchPipeline":
        return self

    def __exit__(self, *exc) -> bool:
        self.flush()
        return False


class CadenceScheduler:
    """One thread for both tick cadences (round 16 single-dispatch).

    Replaces the two per-service ticker daemons (``telemetry.start`` +
    ``tiering.start``). Arming the services' carry cadences lets steady
    serving traffic run the telemetry tick and the sketch decay/estimate
    INSIDE the fused serving dispatch (the runtime's ``lax.cond``
    epilogue) — so under load the ticks cost zero extra dispatches. The
    scheduler thread then only (a) drains both services' queued
    readbacks off the engine lock and (b) self-dispatches a standalone
    ``tick()`` for a service whose armed cadence has gone stale
    (:data:`IDLE_FACTOR` × its interval with no batch carrying the
    epilogue — the zero-traffic fallback), so an idle engine still
    refreshes its hot set and decays its sketch.

    ``poll()`` is the thread body and is callable directly in tests;
    start/stop are idempotent and ``stop`` is registered with
    ``Sentinel.register_shutdown``.
    """

    #: a carry slot is considered missed — and the scheduler
    #: self-dispatches — after this many armed intervals without a tick
    IDLE_FACTOR = 1.5

    def __init__(self, sentinel: Sentinel,
                 telemetry_interval_sec: float = 1.0,
                 tiering_interval_sec: Optional[float] = None):
        from sentinel_tpu.tiering.manager import tier_tick_ms
        self._s = sentinel
        if tiering_interval_sec is None:
            tiering_interval_sec = tier_tick_ms() / 1000.0
        self._tel_ms = max(1, int(telemetry_interval_sec * 1000))
        self._tier_ms = max(1, int(tiering_interval_sec * 1000))
        # drain at twice the fastest cadence so carried readbacks land
        # with at most half an interval of extra latency
        self._poll_s = max(0.02, min(self._tel_ms, self._tier_ms) / 2000.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)

    def poll(self) -> int:
        """One scheduler pass: self-dispatch any stale service's tick,
        then drain both; → entries drained."""
        sn = self._s
        n = 0
        tel = sn.telemetry
        tier = sn.tiering
        if tel.enabled:
            now = sn.clock.now_ms()
            if now - tel.last_tick_ms() >= self._tel_ms * self.IDLE_FACTOR:
                tel.tick()
            n += tel.drain()
        if tier.enabled:
            now = sn.clock.now_ms()
            if (now - tier.last_tick_ms()
                    >= self._tier_ms * self.IDLE_FACTOR):
                tier.tick()
            n += tier.drain()
        # round 17: the overload controller rides the same daemon. Its
        # tick is never device-carried (pure host observe+decide), so
        # the cadence check is exact, not the stale-carry fallback.
        ctl = getattr(sn, "control", None)
        if ctl is not None and ctl.enabled:
            now = sn.clock.now_ms()
            if now - ctl.last_tick_ms() >= ctl.interval_ms:
                ctl.tick()
            n += ctl.drain()
        return n

    def start(self) -> None:
        """Arm both carry cadences and start the daemon (idempotent)."""
        if self._thread is not None:
            return
        self._s.telemetry.arm_carry(self._tel_ms)
        self._s.tiering.arm_carry(self._tier_ms)
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._poll_s):
                try:
                    self.poll()
                except Exception:  # pragma: no cover — keep daemon alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sentinel-cadence")
        self._thread.start()

    def stop(self) -> None:
        """Disarm the carries and join the daemon (idempotent; the
        services' own registered stops handle their final drains)."""
        self._s.telemetry.disarm_carry()
        self._s.tiering.disarm_carry()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
