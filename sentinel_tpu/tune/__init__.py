"""Measurement-driven serving autotuner (round 11).

Five serving-perf rounds exploded the knob space —
``SENTINEL_PIPELINE_DEPTH``, the ``SENTINEL_FRONTEND_*`` batcher set,
donation/staging, the sort-free switch and its table sizing — and
closing the 50M decisions/s bar on real silicon still meant hand-
sweeping them at a tunnel window nobody controls. This package makes
the engine tune itself (ROADMAP item 1's second half):

* :mod:`~sentinel_tpu.tune.knobs` — the typed knob registry
  (type / clamp / default / runtime-vs-trace scope) + the startup
  ``SENTINEL_*`` environment validator;
* :mod:`~sentinel_tpu.tune.search` — the PURE coordinate-descent +
  successive-halving policy core (virtual-clock-driven, injected
  trials, unit-tested on CPU CI);
* :mod:`~sentinel_tpu.tune.runner` — real trials: seeded workload-zoo
  episodes through the full serving path, scored from obs plumbing,
  with a verdict bit-parity spot-check per trial;
* :mod:`~sentinel_tpu.tune.artifact` — ``TUNED.json``: the
  hardware-fingerprinted pinned config ``SENTINEL_TUNED_CONFIG`` loads
  at ``Sentinel`` startup (fingerprint mismatch → defaults, logged).

Operator entry points: ``python -m sentinel_tpu.tune`` runs a sweep;
docs/OPERATIONS.md "Autotuning (round 11)" is the runbook.
"""

from sentinel_tpu.tune.artifact import (           # noqa: F401
    TUNED_CONFIG_ENV, fingerprint, fingerprints_match, load_tuned,
    overrides_for, provenance, resolve_startup, save_tuned,
)
from sentinel_tpu.tune.knobs import (              # noqa: F401
    FRONTEND_KWARG_ENVS, KNOB_BY_ENV, KNOBS, KnobSpec, coerce_config,
    env_overrides, env_strings, known_envs, trace_knobs, validate_environ,
)
from sentinel_tpu.tune.runner import (             # noqa: F401
    ServingTrialRunner, build_space, run_sweep,
)
from sentinel_tpu.tune.search import (             # noqa: F401
    SearchResult, TrialOutcome, TuneSearch, score_outcome,
)

__all__ = [
    "TUNED_CONFIG_ENV", "KNOBS", "KNOB_BY_ENV", "KnobSpec",
    "FRONTEND_KWARG_ENVS", "TuneSearch", "TrialOutcome", "SearchResult",
    "score_outcome", "fingerprint", "fingerprints_match", "save_tuned",
    "load_tuned", "overrides_for", "provenance", "resolve_startup",
    "validate_environ", "known_envs", "coerce_config", "trace_knobs",
    "env_strings", "env_overrides", "ServingTrialRunner", "build_space",
    "run_sweep",
]
