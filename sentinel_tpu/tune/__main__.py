"""``python -m sentinel_tpu.tune`` — run a serving-knob sweep and pin
the winner as a hardware-fingerprinted ``TUNED.json``.

Typical uses (docs/OPERATIONS.md "Autotuning (round 11)"):

    # CPU-CI-sized smoke sweep, default two-knob space
    python -m sentinel_tpu.tune --out TUNED.json

    # chip sweep at a tunnel window: wider space, longer episodes
    python -m sentinel_tpu.tune --out TUNED.json \\
        --knobs SENTINEL_PIPELINE_DEPTH,SENTINEL_FRONTEND_BATCH,\\
SENTINEL_FRONTEND_BUDGET_MS,SENTINEL_SORTFREE_CHUNK \\
        --rate 200000 --rungs 500,2000 --slo-p99-ms 2

    # deploy: every process on this hardware starts pre-tuned
    SENTINEL_TUNED_CONFIG=TUNED.json python my_service.py
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sentinel_tpu.tune",
        description="sweep serving knobs through the real serving path "
                    "and pin a per-hardware TUNED.json")
    ap.add_argument("--out", default="TUNED.json",
                    help="artifact path (default TUNED.json)")
    ap.add_argument("--knobs",
                    default="SENTINEL_PIPELINE_DEPTH,"
                            "SENTINEL_FRONTEND_BATCH",
                    help="comma-separated knob envs to sweep")
    ap.add_argument("--workload", default="steady",
                    help="workload-zoo episode (frontend/workloads.py)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="offered req/s per episode")
    ap.add_argument("--slo-p99-ms", type=float, default=50.0,
                    help="p99 constraint the objective is subject to")
    ap.add_argument("--rungs", default="150,450",
                    help="comma-separated per-rung episode ms "
                         "(successive-halving budgets)")
    ap.add_argument("--passes", type=int, default=1,
                    help="coordinate-descent passes over the space")
    args = ap.parse_args(argv)

    from sentinel_tpu.tune.runner import run_sweep
    out = run_sweep(
        envs=tuple(k.strip() for k in args.knobs.split(",") if k.strip()),
        workload=args.workload, seed=args.seed, rate_rps=args.rate,
        slo_p99_ms=args.slo_p99_ms,
        rung_ms=tuple(int(m) for m in args.rungs.split(",")),
        passes=args.passes, out_path=args.out)
    res = out["result"]
    for rec in res.history:
        print(json.dumps({
            "config": rec.config, "episode_ms": rec.episode_ms,
            "rung": rec.rung, "score": rec.score,
            "decisions_per_s": rec.outcome.decisions_per_s,
            "p99_ms": rec.outcome.p99_ms,
            "parity_ok": rec.outcome.parity_ok}), file=sys.stderr)
    summary = {
        "converged": res.converged,
        "best_config": res.best_config,
        "best_decisions_per_s": res.best_outcome.decisions_per_s,
        "best_p99_ms": res.best_outcome.p99_ms,
        "baseline_decisions_per_s":
            res.baseline_outcome.decisions_per_s,
        "baseline_p99_ms": res.baseline_outcome.p99_ms,
        "trials": out["trials"], "parity_checks": out["parity_checks"],
        "artifact": args.out if out["artifact"] else None,
    }
    print(json.dumps(summary))
    return 0 if res.converged else 1


if __name__ == "__main__":
    raise SystemExit(main())
