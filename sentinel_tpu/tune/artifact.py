"""TUNED.json: the per-hardware pinned-config artifact.

A sweep (:mod:`sentinel_tpu.tune.runner`, ``python -m
sentinel_tpu.tune``) ends by writing one small JSON document — the
winning knob values, the hardware fingerprint they were measured on,
and the scores that justify them — so every later deployment on the
same hardware starts pre-tuned: point ``SENTINEL_TUNED_CONFIG`` at the
artifact and ``Sentinel`` / ``Sentinel.frontend()`` / the benches pick
the knobs up at startup.

Fingerprint (:func:`fingerprint`): backend name, device kind, visible
device count, host CPU cores, and the serving mesh layout
(``parallel/local_shard.mesh_topology()`` — mesh device count, axis,
sharded-or-not). A config tuned for an 8-device row-sharded engine is
NOT a config for a 1-device engine. Deliberately EXCLUDED:
``rows_per_device`` and anything else derived from the
``SentinelConfig`` geometry — geometry is configuration, not hardware,
and folding it in would mean a sweep run at bench geometry could never
warm-start a production engine on the same chips.

Mismatch semantics (documented fallback): :func:`overrides_for` returns
``None`` when the stored fingerprint differs from the live one in ANY
field — the engine then runs on defaults exactly as if
``SENTINEL_TUNED_CONFIG`` were unset, logs the first differing field
via RecordLog, and ticks ``tune.fingerprint_fallback`` so the silent
half of the failure mode (stale artifact after a hardware change) is
observable. A matching load ticks ``tune.config_loaded``.

Precedence (the per-knob override path, docs/OPERATIONS.md
"Autotuning"): explicit env always beats the artifact — a knob whose
``SENTINEL_*`` variable is set in the environment keeps the env value;
the artifact only fills knobs the operator left unset.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

from sentinel_tpu.tune import knobs as knobs_mod

SCHEMA = "sentinel_tune/1"
TUNED_CONFIG_ENV = "SENTINEL_TUNED_CONFIG"


def fingerprint(spec=None, mesh=None) -> Dict:
    """The live hardware/layout fingerprint (see module docstring)."""
    import jax
    dev = jax.devices()[0]
    if mesh is None:
        mesh_block = {"n_devices": 1, "axis": None, "sharded": False}
    elif spec is not None:
        from sentinel_tpu.parallel.local_shard import mesh_topology
        topo = mesh_topology(spec, mesh)
        mesh_block = {k: topo.get(k)
                      for k in ("n_devices", "axis", "sharded")}
    else:
        from sentinel_tpu.parallel.local_shard import MESH_AXIS
        axis = (MESH_AXIS if MESH_AXIS in mesh.axis_names
                else mesh.axis_names[0])
        mesh_block = {"n_devices": int(mesh.shape[axis]), "axis": axis,
                      "sharded": True}
    return {
        "backend": jax.default_backend(),
        "device_kind": str(dev.device_kind),
        "n_devices_visible": int(jax.device_count()),
        "host_cores": int(os.cpu_count() or 1),
        "mesh": mesh_block,
    }


def fingerprints_match(stored: Dict, live: Dict) -> Tuple[bool, str]:
    """(match, first differing field) — exact equality field by field."""
    for k in ("backend", "device_kind", "n_devices_visible", "host_cores"):
        if stored.get(k) != live.get(k):
            return False, f"{k}: {stored.get(k)!r} != {live.get(k)!r}"
    sm, lm = stored.get("mesh") or {}, live.get("mesh") or {}
    for k in ("n_devices", "axis", "sharded"):
        if sm.get(k) != lm.get(k):
            return False, f"mesh.{k}: {sm.get(k)!r} != {lm.get(k)!r}"
    return True, ""


def save_tuned(path: str, *, fingerprint: Dict, knob_values: Dict,
               score: Dict, baseline: Dict, slo_p99_ms: float,
               workload: Dict, trials: int, parity_checks: int) -> Dict:
    """Write the artifact (atomically: temp + rename) and return it."""
    doc = {
        "schema": SCHEMA,
        "fingerprint": fingerprint,
        "knobs": knobs_mod.coerce_config(knob_values),
        "score": score,
        "baseline": baseline,
        "slo_p99_ms": float(slo_p99_ms),
        "workload": workload,
        "trials": int(trials),
        "parity_checks": int(parity_checks),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)
    return doc


def load_tuned(path: str) -> Dict:
    """Read + schema/knob-validate an artifact (raises on malformation —
    a corrupt artifact must fail loudly at the tool layer; the startup
    path below downgrades every failure to a logged fallback)."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: unknown schema {doc.get('schema')!r} "
                         f"(want {SCHEMA})")
    doc["knobs"] = knobs_mod.coerce_config(doc.get("knobs") or {})
    return doc


def overrides_for(doc: Dict, live_fp: Dict) -> Optional[Dict]:
    """Artifact knobs when the fingerprint matches, else ``None``."""
    ok, _why = fingerprints_match(doc.get("fingerprint") or {}, live_fp)
    return dict(doc["knobs"]) if ok else None


def resolve_startup(spec=None, mesh=None, environ=None):
    """Everything ``Sentinel.__init__`` needs, in one call that must
    never raise: (overrides, events).

    * ``overrides`` — knob env → value from a fingerprint-matching
      artifact, MINUS any knob explicitly set in the environment (env
      wins per-knob); ``{}`` when ``SENTINEL_TUNED_CONFIG`` is unset,
      unreadable, or mismatched.
    * ``events`` — ``(counter_key, message)`` pairs for the caller to
      route to RecordLog + obs counters once telemetry exists (the
      knob-validation warnings ride along here too).
    """
    from sentinel_tpu.obs import counters as obs_keys
    env = os.environ if environ is None else environ
    events = [(obs_keys.TUNE_KNOB_REJECTED, w)
              for w in knobs_mod.validate_environ(env)]
    path = env.get(TUNED_CONFIG_ENV, "")
    if not path:
        return {}, events
    try:
        doc = load_tuned(path)
    except (OSError, ValueError) as e:
        events.append((obs_keys.TUNE_FALLBACK,
                       f"tuned config {path}: unreadable ({e}); "
                       f"serving on defaults"))
        return {}, events
    live = fingerprint(spec, mesh)
    ok, why = fingerprints_match(doc.get("fingerprint") or {}, live)
    if not ok:
        events.append((obs_keys.TUNE_FALLBACK,
                       f"tuned config {path}: fingerprint mismatch "
                       f"({why}); serving on defaults"))
        return {}, events
    overrides = {e: v for e, v in doc["knobs"].items() if e not in env}
    events.append((obs_keys.TUNE_LOADED,
                   f"tuned config {path}: loaded "
                   f"{len(overrides)}/{len(doc['knobs'])} knobs "
                   f"(env-set knobs keep their env values)"))
    return overrides, events


def provenance(spec=None, mesh=None, environ=None) -> Dict:
    """The bench-artifact provenance block (round-11 satellite): did a
    tuned config apply, from where, under which fingerprint, and which
    per-knob values — so a BASELINE.md row is reproducible without the
    machine it ran on."""
    env = os.environ if environ is None else environ
    path = env.get(TUNED_CONFIG_ENV, "")
    block: Dict = {"tuned": False, "artifact": path or None}
    if not path:
        return block
    try:
        doc = load_tuned(path)
    except (OSError, ValueError) as e:
        block["error"] = str(e)
        return block
    live = fingerprint(spec, mesh)
    ok, why = fingerprints_match(doc.get("fingerprint") or {}, live)
    if not ok:
        block["fingerprint_mismatch"] = why
        return block
    block.update(tuned=True, fingerprint=doc["fingerprint"],
                 knobs=doc["knobs"])
    return block
