"""Coordinate-descent + successive-halving search over the knob space.

PURE policy core: no engine, no env mutation, no wall clock — trials
are delegated to an injected ``run_trial(config, episode_ms, rung)``
callable and time only enters through an injected
:class:`~sentinel_tpu.core.clock.Clock` (history timestamps + the
optional total budget), so the whole search is deterministic and
unit-testable under ``ManualClock`` on CPU CI
(tests/test_tune.py). The real serving runner
(:mod:`sentinel_tpu.tune.runner`) and ci_gate's gate (j) plug in the
measured trial; the tests plug in synthetic response surfaces.

Search shape — one PASS is coordinate descent over the knobs in
registry order; each coordinate runs SUCCESSIVE HALVING over its
candidate values:

* rung 0 evaluates every candidate at the shortest episode budget;
* the top ``ceil(n/eta)`` scorers survive to the next rung, whose
  episode budget is ``eta``× longer — cheap episodes eliminate the
  clearly-bad values, the expensive verdict is only paid for finalists;
* the last rung's winner is ADOPTED only if it outscores the incumbent
  value measured at the same budget (the incumbent is always a
  candidate, so a sweep can never leave a knob worse than it found it
  — on the measurements; ci_gate's 0.95 band absorbs real-machine
  noise).

Objective (:func:`score_outcome`): maximize decisions/s **subject to**
the p99 SLO — an SLO-violating trial can never outrank a compliant one
(lexicographic: compliant trials compare on throughput, violating
trials compare on how far past the SLO they are), and a trial that
fails the verdict bit-parity spot-check is disqualified outright.

Trials are memoized on (config, episode_ms) so re-measuring the
incumbent at a rung the search already paid for is free.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

from sentinel_tpu.core.clock import Clock
from sentinel_tpu.tune import knobs as knobs_mod

#: Score floor for a disqualified (parity-failing) trial.
DISQUALIFIED = float("-inf")


class TrialOutcome(NamedTuple):
    """What one measured episode reports back to the policy."""

    decisions_per_s: float     # settled requests / episode second (obs)
    p99_ms: Optional[float]    # hist_request p99 (None = no samples)
    parity_ok: bool = True     # verdict bit-parity spot-check vs defaults
    meta: dict = {}            # runner extras (shed, stalls, ...)


class TrialRecord(NamedTuple):
    """One search-history row (``SearchResult.history``)."""

    config: Dict[str, object]
    episode_ms: int
    rung: int
    outcome: TrialOutcome
    score: float
    t_ms: int                  # policy-clock stamp


class Elimination(NamedTuple):
    """One halving cut (``SearchResult.eliminations``): which candidate
    values of which knob were dropped at which rung."""

    env: str
    rung: int
    survivors: Tuple
    eliminated: Tuple


class SearchResult(NamedTuple):
    best_config: Dict[str, object]
    best_outcome: TrialOutcome
    baseline_outcome: TrialOutcome
    history: Tuple[TrialRecord, ...]
    eliminations: Tuple[Elimination, ...]
    converged: bool            # every trial ran, no parity failure


def score_outcome(outcome: TrialOutcome, slo_p99_ms: float) -> float:
    """Lexicographic objective, flattened to one float (see module
    docstring). Compliant scores are positive throughput; violating
    scores are negative and ordered by SLO overshoot, so the two bands
    can never interleave."""
    if not outcome.parity_ok:
        return DISQUALIFIED
    p99 = outcome.p99_ms
    if p99 is not None and p99 > slo_p99_ms:
        return -(p99 - slo_p99_ms)     # closer to the SLO ranks higher
    return max(outcome.decisions_per_s, 0.0)


def _config_key(config: Dict[str, object]) -> Tuple:
    return tuple(sorted(config.items()))


class TuneSearch:
    """One configured search over ``space`` (a sequence of
    :class:`~sentinel_tpu.tune.knobs.KnobSpec`, each with a non-empty
    candidate grid).

    ``rung_ms`` sets the per-rung episode budgets explicitly (its length
    caps the number of halving rungs); ``eta`` is the halving factor.
    """

    def __init__(self, space: Sequence[knobs_mod.KnobSpec], *,
                 slo_p99_ms: float, clock: Clock,
                 rung_ms: Sequence[int] = (150, 450),
                 eta: int = 2, passes: int = 1):
        if not space:
            raise ValueError("empty knob space")
        for spec in space:
            if not spec.values:
                raise ValueError(f"{spec.env} has no candidate grid")
        self.space = tuple(space)
        self.slo_p99_ms = float(slo_p99_ms)
        self.clock = clock
        self.rung_ms = tuple(int(m) for m in rung_ms)
        self.eta = max(2, int(eta))
        self.passes = max(1, int(passes))
        self._memo: Dict[Tuple, TrialOutcome] = {}
        self._history: List[TrialRecord] = []
        self._eliminations: List[Elimination] = []
        self._parity_failed = False

    # ------------------------------------------------------------------

    def _measure(self, run_trial: Callable, config: Dict[str, object],
                 episode_ms: int, rung: int) -> Tuple[TrialOutcome, float]:
        key = (_config_key(config), episode_ms)
        outcome = self._memo.get(key)
        if outcome is None:
            outcome = run_trial(dict(config), episode_ms, rung)
            self._memo[key] = outcome
            s = score_outcome(outcome, self.slo_p99_ms)
            if not outcome.parity_ok:
                self._parity_failed = True
            self._history.append(TrialRecord(
                dict(config), episode_ms, rung, outcome, s,
                self.clock.now_ms()))
            return outcome, s
        return outcome, score_outcome(outcome, self.slo_p99_ms)

    def _halve_coordinate(self, run_trial: Callable, spec: knobs_mod.KnobSpec,
                          base: Dict[str, object], incumbent) -> Tuple:
        """Successive halving over one knob's candidates (incumbent value
        always included). Returns (winner_value, winner_score)."""
        candidates = list(dict.fromkeys(
            (incumbent,) + tuple(spec.coerce(v) for v in spec.values)))
        scores: Dict[object, float] = {}
        for rung, budget in enumerate(self.rung_ms):
            for v in candidates:
                cfg = dict(base)
                cfg[spec.env] = v
                _, scores[v] = self._measure(run_trial, cfg, budget, rung)
            if len(candidates) > 1:
                ranked = sorted(candidates, key=lambda v: scores[v],
                                reverse=True)
                keep = max(1, math.ceil(len(ranked) / self.eta))
                # never eliminate below 2 before the final rung: the
                # last rung must still be a comparison, not a coronation
                if rung < len(self.rung_ms) - 1:
                    keep = max(keep, min(2, len(ranked)))
                survivors, cut = ranked[:keep], ranked[keep:]
                if cut:
                    self._eliminations.append(Elimination(
                        spec.env, rung, tuple(survivors), tuple(cut)))
                candidates = survivors
        best = max(candidates, key=lambda v: scores[v])
        return best, scores[best]

    # ------------------------------------------------------------------

    def run(self, run_trial: Callable[[Dict[str, object], int, int],
                                      TrialOutcome]) -> SearchResult:
        """Execute the search; see the module docstring for the shape."""
        final_ms = self.rung_ms[-1]
        # incumbent = the registry defaults restricted to the space
        # (None-default knobs start from their first grid value)
        current: Dict[str, object] = {}
        for spec in self.space:
            v = spec.default if spec.default is not None \
                else spec.coerce(spec.values[0])
            current[spec.env] = spec.coerce(v)
        baseline, baseline_score = self._measure(
            run_trial, current, final_ms, rung=len(self.rung_ms) - 1)
        best_score = baseline_score
        for _ in range(self.passes):
            for spec in self.space:
                winner, w_score = self._halve_coordinate(
                    run_trial, spec, current, current[spec.env])
                if w_score > best_score:
                    current = dict(current)
                    current[spec.env] = winner
                    best_score = w_score
        best_outcome = self._memo[(_config_key(current), final_ms)]
        converged = (not self._parity_failed
                     and best_score > DISQUALIFIED)
        return SearchResult(
            best_config=current, best_outcome=best_outcome,
            baseline_outcome=baseline,
            history=tuple(self._history),
            eliminations=tuple(self._eliminations),
            converged=converged)
