"""The measured half of the autotuner: real serving episodes as trials.

Each trial replays one seeded workload-zoo episode through the FULL
serving path — ``AdaptiveBatcher`` → ``DispatchPipeline`` → engine
dispatch (meshed when the episode runs on a meshed engine) — by riding
:func:`benchmarks.serving_bench.run_workload`, the same open-loop
harness ci_gate's SLO gates already trust. Scores come from the
engine's own obs plumbing surfaced in that harness's metrics dict
(``hist_request`` p99 via ``p99_obs_ms``, settled-request throughput
via ``decisions_per_s``, shed/stall counters in ``meta``), never from
ad-hoc wall clocks around the replay.

Knob application per trial: runtime-scope knobs (pipeline depth, the
frontend set) pass as explicit batcher kwargs — a fresh
batcher/pipeline over the episode engine reconfigures them in place;
trace-scope knobs (donation, staging, sortfree and its sizing) apply
through :func:`~sentinel_tpu.tune.knobs.env_overrides` so the fresh
engine each episode builds compiles them in.

Guardrail (after every trial): a verdict bit-parity spot-check against
the DEFAULT config — a fixed seeded batch sequence driven through a
small ``ManualClock`` engine under the trial's trace knobs must produce
byte-identical (allow, reason, wait_ms) streams to the default-config
engine. Runtime knobs cannot change that stream by construction (they
batch the same events differently; the check drives the raw engine
below the batcher), so the check memoizes per trace-knob combination —
every trial still reports a ``parity_ok`` verdict and any failure
disqualifies its config (``tune.parity_fail``).

:func:`run_sweep` is the one-call driver ci_gate's gate (j) and
``python -m sentinel_tpu.tune`` share: build the space, search, write
``TUNED.json``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple

from sentinel_tpu.core.clock import Clock, ManualClock
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.tune import artifact as artifact_mod
from sentinel_tpu.tune import knobs as knobs_mod
from sentinel_tpu.tune.search import TrialOutcome, TuneSearch

#: AdaptiveBatcher kwarg ← knob env, for the runtime-scope trial knobs
#: (run_workload's explicit kwargs — constructor kwargs beat env).
_RUNTIME_KWARGS = {
    "SENTINEL_FRONTEND_BATCH": "batch_max",
    "SENTINEL_FRONTEND_DEADLINE_MS": "deadline_ms",
    "SENTINEL_FRONTEND_BUDGET_MS": "budget_ms",
    "SENTINEL_FRONTEND_IDLE_MS": "idle_ms",
    "SENTINEL_FRONTEND_QUEUE": "queue_max",
    "SENTINEL_PIPELINE_DEPTH": "depth",
}


def _import_run_workload():
    """benchmarks/ is not a package dir on sys.path by default."""
    here = Path(__file__).resolve().parents[2]
    if str(here) not in sys.path:
        sys.path.insert(0, str(here))
    from benchmarks.serving_bench import run_workload
    return run_workload


def _verdict_signature(trace_cfg: Dict[str, object], *, seed: int,
                       steps: int, events: int) -> bytes:
    """Byte stream of every verdict a fixed seeded batch sequence
    produces on a small ManualClock engine built under ``trace_cfg`` —
    the comparable for the bit-parity spot-check. Deterministic: virtual
    clock, seeded numpy, fixed geometry."""
    import numpy as np
    import sentinel_tpu as stpu

    rng = np.random.default_rng(seed)
    clk = ManualClock(start_ms=1_700_000_000_000)
    with knobs_mod.env_overrides(trace_cfg):
        sph = stpu.Sentinel(stpu.load_config(
            max_resources=256, max_origins=32, max_flow_rules=32,
            max_degrade_rules=8, max_authority_rules=8), clock=clk)
        # tight + generous rules so the stream exercises PASS, BLOCK and
        # pacing verdicts (a parity check over all-pass proves nothing)
        sph.load_flow_rules(
            [stpu.FlowRule(resource="tune/hot", count=25.0)]
            + [stpu.FlowRule(resource=f"tune/{i}", count=1e6)
               for i in range(8)])
        names = ["tune/hot"] * 4 + [f"tune/{i}" for i in range(8)]
        out = bytearray()
        for _ in range(steps):
            res = rng.choice(names, size=events).tolist()
            acquire = rng.integers(1, 3, size=events).astype(np.int32)
            prio = (rng.random(events) < 0.1)
            origins = ["tune-app" if b else None
                       for b in rng.random(events) < 0.3]
            v = sph.entry_batch_nowait(
                res, acquire=acquire, prioritized=prio,
                origins=origins).result()
            out += np.asarray(v.allow).tobytes()
            out += np.asarray(v.reason).tobytes()
            out += np.asarray(v.wait_ms).tobytes()
            clk.advance_ms(int(rng.integers(50, 300)))
        sph.close()
    return bytes(out)


class ServingTrialRunner:
    """``run_trial`` callable for :class:`TuneSearch` over real serving
    episodes (see module docstring). ``counters`` is any
    :class:`~sentinel_tpu.obs.counters.CounterSet` to receive the
    ``tune.trial`` / ``tune.parity_fail`` ticks (the sweep CLI and gate
    (j) read it back for the artifact/report)."""

    def __init__(self, *, workload: str = "steady", seed: int = 11,
                 rate_rps: float = 2000.0, counters=None,
                 parity_seed: int = 5, parity_steps: int = 3,
                 parity_events: int = 64):
        self.workload = workload
        self.seed = int(seed)
        self.rate_rps = float(rate_rps)
        self.counters = counters if counters is not None \
            else obs_keys.CounterSet()
        self._parity_seed = parity_seed
        self._parity_steps = parity_steps
        self._parity_events = parity_events
        self._parity_ref: Optional[bytes] = None
        self._parity_memo: Dict[Tuple, bool] = {}
        self.trials = 0
        self.parity_checks = 0

    # ------------------------------------------------------------------

    def _parity_ok(self, config: Dict[str, object]) -> bool:
        trace_cfg = knobs_mod.trace_knobs(config)
        key = tuple(sorted(trace_cfg.items()))
        memo = self._parity_memo.get(key)
        if memo is not None:
            return memo
        if self._parity_ref is None:
            self._parity_ref = _verdict_signature(
                {}, seed=self._parity_seed, steps=self._parity_steps,
                events=self._parity_events)
        got = _verdict_signature(
            trace_cfg, seed=self._parity_seed, steps=self._parity_steps,
            events=self._parity_events)
        ok = got == self._parity_ref
        self._parity_memo[key] = ok
        self.parity_checks += 1
        if not ok:
            self.counters.add(obs_keys.TUNE_PARITY_FAIL)
        return ok

    def __call__(self, config: Dict[str, object], episode_ms: int,
                 rung: int) -> TrialOutcome:
        run_workload = _import_run_workload()
        kwargs = {}
        for env, kw in _RUNTIME_KWARGS.items():
            if env in config:
                kwargs[kw] = config[env]
        trace_cfg = knobs_mod.trace_knobs(config)
        with knobs_mod.env_overrides(trace_cfg):
            m = run_workload(self.workload, seed=self.seed,
                             duration_ms=float(episode_ms),
                             rate_rps=self.rate_rps, **kwargs)
        self.trials += 1
        self.counters.add(obs_keys.TUNE_TRIAL)
        ok = self._parity_ok(config)
        return TrialOutcome(
            decisions_per_s=float(m.get("decisions_per_s") or 0.0),
            p99_ms=m.get("p99_obs_ms"),
            parity_ok=ok,
            meta={"shed": m.get("shed", 0),
                  "pipe_stall": m.get("pipe_stall", 0),
                  "deadline_miss": m.get("deadline_miss", 0),
                  "completed": m.get("completed", 0),
                  "rung": rung})


def build_space(envs: Sequence[str],
                grids: Optional[Dict[str, Sequence]] = None):
    """Knob names (+ optional per-knob grid overrides) → search space."""
    space = []
    for env in envs:
        spec = knobs_mod.KNOB_BY_ENV.get(env)
        if spec is None:
            raise ValueError(f"unknown tuning knob {env!r}")
        if grids and env in grids:
            spec = spec._replace(values=tuple(grids[env]))
        space.append(spec)
    return space


def run_sweep(*, envs: Sequence[str] = ("SENTINEL_PIPELINE_DEPTH",
                                        "SENTINEL_FRONTEND_BATCH"),
              grids: Optional[Dict[str, Sequence]] = None,
              workload: str = "steady", seed: int = 11,
              rate_rps: float = 2000.0, slo_p99_ms: float = 50.0,
              rung_ms: Sequence[int] = (150, 450), eta: int = 2,
              passes: int = 1, out_path: Optional[str] = None,
              clock: Optional[Clock] = None) -> Dict:
    """One full sweep: search the space through real serving episodes
    and (optionally) pin the winner as a ``TUNED.json`` artifact.
    Returns ``{"result": SearchResult, "artifact": doc|None, ...}``."""
    space = build_space(envs, grids)
    runner = ServingTrialRunner(workload=workload, seed=seed,
                                rate_rps=rate_rps)
    search = TuneSearch(space, slo_p99_ms=slo_p99_ms,
                        clock=clock or Clock(), rung_ms=rung_ms, eta=eta,
                        passes=passes)
    result = search.run(runner)
    doc = None
    if out_path and result.converged:
        doc = artifact_mod.save_tuned(
            out_path,
            fingerprint=artifact_mod.fingerprint(),
            knob_values=result.best_config,
            score={"decisions_per_s": result.best_outcome.decisions_per_s,
                   "p99_ms": result.best_outcome.p99_ms},
            baseline={
                "decisions_per_s": result.baseline_outcome.decisions_per_s,
                "p99_ms": result.baseline_outcome.p99_ms},
            slo_p99_ms=slo_p99_ms,
            workload={"name": workload, "seed": seed,
                      "rate_rps": rate_rps,
                      "rung_ms": list(rung_ms)},
            trials=runner.trials,
            parity_checks=runner.parity_checks)
    return {"result": result, "artifact": doc,
            "counters": runner.counters.snapshot(),
            "trials": runner.trials,
            "parity_checks": runner.parity_checks}
