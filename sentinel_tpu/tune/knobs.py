"""The serving knob registry: every ``SENTINEL_*`` tuning knob, typed.

Five serving-perf rounds scattered env reads across the tree —
``pipeline_depth()`` in runtime.py, the ``frontend_*`` clamps in
frontend/batcher.py, the claim-table sizing in ops/sortfree.py, the
donation/staging booleans. Each read site stays authoritative for its
own hot path (no extra import, no indirection on dispatch); this module
is the REGISTRY over them: one :class:`KnobSpec` per knob declaring
type, clamp range, default, and — the property the autotuner pivots on —
whether the knob is **runtime-applicable** (a new
:class:`~sentinel_tpu.frontend.AdaptiveBatcher` /
:class:`~sentinel_tpu.serving.DispatchPipeline` over the same engine
picks it up: depth, the frontend batch/deadline/budget/idle/queue set)
or **trace-time** (baked into the jitted step programs or the engine's
construction-time buffers: donation, host staging, the sort-free switch
and its table/chunk sizing — changing one forces a fresh ``Sentinel``
per trial).

``tests/test_tune.py::test_registry_matches_runtime_clamps`` pins every
spec's (default, clamp) against the real read-site helper under extreme
env values, so the registry can never silently drift from the code it
describes.

The registry also powers startup validation (round-11 satellite):
:func:`validate_environ` scans ``os.environ`` for ``SENTINEL_*`` keys
and reports typos (``SENTINEL_PIPLINE_DEPTH`` was silently ignored
before this round) and out-of-clamp or unparsable values — surfaced via
RecordLog and the ``tune.knob_rejected`` counter at ``Sentinel``
construction.
"""

from __future__ import annotations

import contextlib
import difflib
import os
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

SCOPE_RUNTIME = "runtime"   # new batcher/pipeline picks it up in place
SCOPE_TRACE = "trace"       # baked into jitted programs / engine buffers

#: Spellings the ``_env_on`` boolean reader recognizes (runtime.py) —
#: anything else is "on", which is exactly the silent-typo trap the
#: validator warns about.
_BOOL_FALSE = ("0", "off", "false", "disable", "disabled")
_BOOL_TRUE = ("1", "on", "true", "yes", "enable", "enabled")


class KnobSpec(NamedTuple):
    """One tunable serving knob (see module docstring for field roles)."""

    env: str                       # the environment variable
    kind: str                      # "int" | "float" | "bool"
    default: object                # value when unset (None = auto/derived)
    lo: Optional[float]            # clamp bounds (None for bool/auto)
    hi: Optional[float]
    scope: str                     # SCOPE_RUNTIME | SCOPE_TRACE
    values: Tuple                  # default sweep grid for the search
    doc: str                       # one-line operator description

    def parse(self, raw: str):
        """(value, ok): the value the READ SITE would actually use for
        ``raw`` (clamped — the helpers clamp rather than reject), and
        whether ``raw`` was well-formed and inside the clamp range."""
        if self.kind == "bool":
            v = raw.lower() not in _BOOL_FALSE
            ok = raw.lower() in _BOOL_FALSE + _BOOL_TRUE
            return v, ok
        cast = float if self.kind == "float" else int
        try:
            v = cast(raw)
        except ValueError:
            return self.default, False
        clamped = min(self.hi, max(self.lo, v))
        if self.kind == "int":
            clamped = int(clamped)
        return clamped, clamped == v

    def coerce(self, v):
        """Clamp an artifact/search value into this knob's domain."""
        if self.kind == "bool":
            return bool(v)
        cast = float if self.kind == "float" else int
        v = cast(v)
        if self.lo is not None:
            v = min(self.hi, max(self.lo, v))
        return cast(v)


#: The tunable registry. Clamp bounds and defaults MIRROR the read-site
#: helpers (named per knob below); test_tune.py pins the agreement.
KNOBS: Tuple[KnobSpec, ...] = (
    # runtime.pipeline_depth() — dispatch-pipeline in-flight window
    KnobSpec("SENTINEL_PIPELINE_DEPTH", "int", 2, 1, 64, SCOPE_RUNTIME,
             (1, 2, 4, 8),
             "depth-k dispatch window (serving.py DispatchPipeline)"),
    # frontend/batcher.py frontend_batch_max()
    KnobSpec("SENTINEL_FRONTEND_BATCH", "int", 256, 1, 1 << 16,
             SCOPE_RUNTIME, (64, 128, 256, 512),
             "adaptive-batcher B_max (flush-when-full bound)"),
    # frontend/batcher.py frontend_deadline_ms()
    KnobSpec("SENTINEL_FRONTEND_DEADLINE_MS", "int", 25, 1, 60_000,
             SCOPE_RUNTIME, (10, 25, 50),
             "default per-request latency budget"),
    # frontend/batcher.py frontend_budget_ms()
    KnobSpec("SENTINEL_FRONTEND_BUDGET_MS", "int", 3, 0, 10_000,
             SCOPE_RUNTIME, (1, 3, 6),
             "dispatch+device reserve subtracted from each deadline"),
    # frontend/batcher.py frontend_idle_ms()
    KnobSpec("SENTINEL_FRONTEND_IDLE_MS", "float", 1.0, 0.0, 1000.0,
             SCOPE_RUNTIME, (0.5, 1.0, 2.0),
             "arrival gap after which a partial batch flushes"),
    # frontend/batcher.py frontend_queue_max() — default derives from
    # B_max (8·B_max), so the registry default is None ("auto")
    KnobSpec("SENTINEL_FRONTEND_QUEUE", "int", None, 1, 1 << 22,
             SCOPE_RUNTIME, (),
             "backpressure bound (default 8·B_max)"),
    # runtime.donation_enabled() — buffer donation on the jitted steps
    KnobSpec("SENTINEL_DONATE", "bool", True, None, None, SCOPE_TRACE,
             (True, False),
             "donate engine-state buffers into each step's output"),
    # runtime.host_staging_enabled() — preallocated host batch columns
    KnobSpec("SENTINEL_HOST_STAGING", "bool", True, None, None,
             SCOPE_TRACE, (True, False),
             "reuse pinned host staging rings for batch columns"),
    # runtime.sortfree_enabled() — hash-bucketed general aggregation
    KnobSpec("SENTINEL_SORTFREE", "bool", True, None, None, SCOPE_TRACE,
             (True, False),
             "sort-free claim-cascade general path (vs sorted reference)"),
    # runtime.single_dispatch_enabled() — round 16: fold the tiering
    # sketch observe (and the lax.cond telemetry/decay epilogue on the
    # fused path) into the decide programs so a steady-state batch costs
    # ONE device dispatch; =0 is the operator escape hatch restoring the
    # pre-r16 two-dispatch composition byte-for-byte (compile-cache keys
    # included)
    KnobSpec("SENTINEL_SINGLE_DISPATCH", "bool", True, None, None,
             SCOPE_TRACE, (True, False),
             "fuse sketch observe + tick epilogue into the decide dispatch"),
    # ops/sortfree.py table_bits() — auto-sized from the batch when
    # unset (default None); an explicit override clamps to [1, 18] (the
    # sub-6 range exists for the collision-forcing parity tests)
    KnobSpec("SENTINEL_SORTFREE_BITS", "int", None, 1, 18, SCOPE_TRACE,
             (8, 10, 12, 14),
             "claim-table size override (2^bits buckets)"),
    # ops/sortfree.py chunk_size() — clamp [16, 4096]
    KnobSpec("SENTINEL_SORTFREE_CHUNK", "int", 256, 16, 4096, SCOPE_TRACE,
             (64, 256, 1024),
             "claim-cascade scan chunk (one [m, m] compare per step)"),
    # tiering/manager.py tier_hot_rows() — device hot-tier row target;
    # default None = the engine's max_resources (tiering keeps the whole
    # table hot). Empty sweep grid: sizing is workload-skew-bound, not a
    # latency/throughput trade the halving search can score.
    KnobSpec("SENTINEL_HOT_ROWS", "int", None, 64, 1 << 24, SCOPE_RUNTIME,
             (),
             "device hot-tier size (rows the ticker keeps resident)"),
    # tiering/manager.py tier_sketch_bits() — count-min width = 2^bits
    KnobSpec("SENTINEL_SKETCH_BITS", "int", 12, 4, 22, SCOPE_RUNTIME,
             (),
             "count-min sketch width exponent (2^bits counters per row)"),
    # tiering/manager.py tier_sketch_rows()
    KnobSpec("SENTINEL_SKETCH_ROWS", "int", 4, 1, 8, SCOPE_RUNTIME,
             (),
             "count-min sketch depth (independent hash rows)"),
    # tiering/manager.py tier_tick_ms() — promotion/demotion cadence
    KnobSpec("SENTINEL_TIER_TICK_MS", "int", 200, 10, 60_000,
             SCOPE_RUNTIME, (),
             "tiering ticker period (sketch decay + demote scan)"),
    # control/loop.py — round-17 overload controller (empty sweep grids:
    # the control law is an SLO policy, not a latency/throughput trade
    # the halving search can score; the gate (n) episode pins behavior)
    KnobSpec("SENTINEL_CONTROL_INTERVAL_MS", "int", 1000, 50, 60_000,
             SCOPE_RUNTIME, (),
             "overload-controller tick cadence (control/loop.py)"),
    KnobSpec("SENTINEL_CONTROL_P99_HI_MS", "float", 20.0, 1.0, 60_000.0,
             SCOPE_RUNTIME, (),
             "interval p99 above which the controller sheds (AIMD MD)"),
    KnobSpec("SENTINEL_CONTROL_P99_LO_MS", "float", 10.0, 0.5, 60_000.0,
             SCOPE_RUNTIME, (),
             "interval p99 below which admission recovers (AIMD AI)"),
    KnobSpec("SENTINEL_CONTROL_MIN_ADMIT", "float", 0.05, 0.01, 1.0,
             SCOPE_RUNTIME, (),
             "admission-fraction floor (the shed never black-holes)"),
    KnobSpec("SENTINEL_CONTROL_COOLDOWN_MS", "int", 2000, 100, 600_000,
             SCOPE_RUNTIME, (),
             "per-action repeat bound (anti-flap, with the hysteresis band)"),
    KnobSpec("SENTINEL_CONTROL_DEGRADE_RT_MS", "float", 0.0, 0.0, 60_000.0,
             SCOPE_RUNTIME, (),
             "per-resource RT tail (p99) bound forcing breaker arcs (0 = off)"),
    # obs/resource_hist.py — round-20 device-resident per-resource RT
    # histograms. Both trace-scope: they size the ``rt_hist`` state leaf
    # and are baked into the fused step programs. Empty sweep grids —
    # observability switches, not latency/throughput trades.
    KnobSpec("SENTINEL_RESOURCE_HIST_DISABLE", "bool", False, None, None,
             SCOPE_TRACE, (),
             "drop the per-resource RT histogram table (pre-r20 programs)"),
    KnobSpec("SENTINEL_RESOURCE_HIST_BUCKETS", "int", 32, 8, 32,
             SCOPE_TRACE, (),
             "RT histogram bucket count (log2 ms buckets, int32-safe cap)"),
)

KNOB_BY_ENV: Dict[str, KnobSpec] = {k.env: k for k in KNOBS}

#: AdaptiveBatcher constructor kwarg ↔ knob env (Sentinel.frontend()
#: fills unset kwargs from a loaded TUNED.json through this map).
FRONTEND_KWARG_ENVS: Tuple[Tuple[str, str], ...] = (
    ("batch_max", "SENTINEL_FRONTEND_BATCH"),
    ("deadline_ms", "SENTINEL_FRONTEND_DEADLINE_MS"),
    ("budget_ms", "SENTINEL_FRONTEND_BUDGET_MS"),
    ("idle_ms", "SENTINEL_FRONTEND_IDLE_MS"),
    ("queue_max", "SENTINEL_FRONTEND_QUEUE"),
    ("depth", "SENTINEL_PIPELINE_DEPTH"),
)

#: Recognized NON-tunable operational keys (observability, multihost
#: bootstrap, cold start, native path, ...) — listed so the validator
#: can tell a typo from a real operational knob. Value checking for
#: these is parse-only where a caster is declared.
OPERATIONAL_ENVS: Dict[str, Optional[type]] = {
    "SENTINEL_OBS_DISABLE": None,
    "SENTINEL_TRACE_SAMPLE": float,
    "SENTINEL_FLIGHT_DISABLE": None,
    "SENTINEL_FLIGHT_WINDOW_MS": int,
    "SENTINEL_FLIGHT_P99_MS": float,
    "SENTINEL_FLIGHT_BLOCK_BURST": int,
    "SENTINEL_TELEMETRY_K": int,
    "SENTINEL_TELEMETRY_DISABLE": None,
    "SENTINEL_CONTROL_DISABLE": None,
    "SENTINEL_TIERING_DISABLE": None,
    "SENTINEL_TIER_COLD_MAX": int,
    "SENTINEL_FIRST_LOAD_TIMEOUT_S": float,
    "SENTINEL_FIRST_LOAD_RETRIES": int,
    "SENTINEL_COMPILE_CACHE": None,
    "SENTINEL_INIT_MODE": None,
    "SENTINEL_INIT_WAIT_TIMEOUT_S": float,
    "SENTINEL_COORDINATOR": None,
    "SENTINEL_NUM_PROCESSES": int,
    "SENTINEL_PROCESS_ID": int,
    "SENTINEL_LOCAL_DEVICES": int,
    "SENTINEL_MH_PLATFORM": None,
    "SENTINEL_DASH_AGENT_TIMEOUT_S": float,
    "SENTINEL_DEMO_ONESHOT": None,
    "SENTINEL_TUNED_CONFIG": None,
    "SENTINEL_TPU_NATIVE": None,
    "SENTINEL_TPU_LOG_DIR": None,
    "SENTINEL_TPU_PLUGINS": None,
    "SENTINEL_TPU_CONFIG_FILE": None,
}


def _config_field_envs() -> frozenset:
    """``SENTINEL_TPU_<FIELD>`` keys from the SentinelConfig dataclass
    (core/config.py maps the prefix onto config fields)."""
    import dataclasses
    from sentinel_tpu.core.config import SentinelConfig
    return frozenset("SENTINEL_TPU_" + f.name.upper()
                     for f in dataclasses.fields(SentinelConfig))


def known_envs() -> frozenset:
    """Every recognized ``SENTINEL_*`` environment key."""
    return (frozenset(KNOB_BY_ENV) | frozenset(OPERATIONAL_ENVS)
            | _config_field_envs())


def validate_environ(environ=None) -> List[str]:
    """Scan for ``SENTINEL_*`` keys that are unknown (typos — with a
    did-you-mean when close), unparsable, or outside a knob's clamp
    range. Returns one warning string per finding; the caller
    (``Sentinel.__init__``) routes them to RecordLog and ticks
    ``tune.knob_rejected`` once per finding."""
    env = os.environ if environ is None else environ
    known = known_envs()
    warnings: List[str] = []
    for key in sorted(k for k in env if k.startswith("SENTINEL_")):
        raw = env[key]
        if key not in known:
            hint = difflib.get_close_matches(key, known, n=1, cutoff=0.75)
            suffix = f" (did you mean {hint[0]}?)" if hint else ""
            warnings.append(
                f"unknown env knob {key}={raw!r} is ignored{suffix}")
            continue
        spec = KNOB_BY_ENV.get(key)
        if spec is not None:
            used, ok = spec.parse(raw)
            if not ok:
                warnings.append(
                    f"env knob {key}={raw!r} is outside "
                    f"[{spec.lo}, {spec.hi}]" if spec.kind != "bool"
                    else f"env knob {key}={raw!r} is not a recognized "
                    f"boolean spelling (reads as "
                    f"{'on' if used else 'off'})")
            continue
        caster = OPERATIONAL_ENVS.get(key)
        if caster is not None and raw:
            try:
                caster(raw)
            except ValueError:
                warnings.append(
                    f"env knob {key}={raw!r} does not parse as "
                    f"{caster.__name__}")
    return warnings


def defaults() -> Dict[str, object]:
    """env → default value for every knob with a concrete default."""
    return {k.env: k.default for k in KNOBS if k.default is not None}


def coerce_config(knob_values: Dict[str, object]) -> Dict[str, object]:
    """Validate + clamp an artifact/search config dict; unknown knob
    names raise (an artifact must never smuggle arbitrary env keys)."""
    out: Dict[str, object] = {}
    for env, v in knob_values.items():
        spec = KNOB_BY_ENV.get(env)
        if spec is None:
            raise ValueError(f"unknown tuning knob {env!r}")
        out[env] = spec.coerce(v)
    return out


def trace_knobs(knob_values: Dict[str, object]) -> Dict[str, object]:
    """The trace-scope subset — the part whose change forces a fresh
    engine (the search keys its engine/parity caches on this)."""
    return {e: v for e, v in knob_values.items()
            if KNOB_BY_ENV[e].scope == SCOPE_TRACE}


def env_strings(knob_values: Dict[str, object]) -> Dict[str, str]:
    """Knob values → the env-var string encoding the read sites parse."""
    out = {}
    for env, v in knob_values.items():
        if KNOB_BY_ENV[env].kind == "bool":
            out[env] = "1" if v else "0"
        else:
            out[env] = repr(v) if isinstance(v, float) else str(v)
    return out


@contextlib.contextmanager
def env_overrides(knob_values: Dict[str, object]):
    """Apply a trial config through the env read sites (the ONLY way
    trace-time knobs reach the jitted programs), restoring the previous
    values on exit — the sweep harness's save/restore discipline, same
    pattern as ci_gate's sortfree parity probe."""
    strs = env_strings(knob_values)
    saved = {k: os.environ.get(k) for k in strs}
    os.environ.update(strs)
    try:
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
