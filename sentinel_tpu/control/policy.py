"""The pure overload-policy core: telemetry in, typed actions out.

No engine, no asyncio, no wall clock — every method takes explicit
timestamps, so tests drive the whole control law under a ManualClock
(tests/test_control.py). The runner (:mod:`~sentinel_tpu.control.loop`)
feeds it :class:`Observation` rows built from the round-12 per-second
telemetry timeline (pass/block/RT-sum ticks), the rolling
``hist_request`` latency histogram, and the ingest queue depth; it
emits :class:`ShedRate` / :class:`RetuneBatcher` / :class:`Degrade`
actions for the actuators to apply.

Control law (BBR-flavored AIMD):

* **Estimation** — :class:`HistDeltaP99` diffs consecutive cumulative
  histogram snapshots so the controller reacts to the p99 of the LAST
  interval, not the process-lifetime percentile (which goes numb after
  minutes of history); :class:`WindowedFilter` keeps BBR-style
  windowed-max delivery rate and windowed-min RT estimates, the
  headroom pair the snapshot surface reports.
* **Decision** — multiplicative backoff of the admitted fraction when
  the interval p99 crosses ``p99_hi_ms`` (or the ingest queue passes
  ``queue_hi_frac`` of its bound), additive recovery when it falls
  below ``p99_lo_ms``; the [lo, hi] band between them is the
  hysteresis hold — no action, no flapping. Every action key carries
  its own ``cooldown_ms`` stamp, so a decision cannot repeat faster
  than the system can respond to it.
* **Degrade** — per-resource three-state trackers over device-measured
  RT: ``degrade_bad_ticks`` consecutive bad intervals force the
  resource's breaker OPEN, ``degrade_hold_ms`` later it is probed
  HALF_OPEN, and one good interval closes it (one bad re-opens).
  Disabled unless ``degrade_rt_ms`` > 0. Round 20: the tracked signal
  is the per-resource **interval p99** recovered from the
  device-resident RT histogram table (``Observation.resource_p99``,
  built by the loop's :class:`~sentinel_tpu.obs.resource_hist.\
ResourceTailTracker`); ``degrade_rt_ms`` is therefore a TAIL bound. A
  mean hides the slow-consumer pathology — 2 stuck calls at 500 ms
  among 98 fast ones average ~12 ms but p99 ≈ 500 ms. When histograms
  are disabled the policy falls back to the pre-r20 hot-set mean RT
  (``Observation.resource_rt``), preserving bit-parity.
"""

from __future__ import annotations

import collections
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from sentinel_tpu.obs.hist import BASE_NS, NUM_BUCKETS

# Degrade.transition values (applied via Sentinel.force_breaker)
DEG_OPEN = "open"
DEG_HALF_OPEN = "half_open"
DEG_CLOSE = "close"


class Observation(NamedTuple):
    """One controller tick's view of the system (all host-side)."""

    ts_ms: int                      # clock stamp of the tick
    pass_per_s: float               # last landed second's pass count
    block_per_s: float              # last landed second's block count
    rt_avg_ms: float                # device RT mean over that second
    p99_ms: float                   # interval p99 of hist_request (0=idle)
    queue_depth: int                # frontend pending (queued + inflight)
    queue_max: int                  # frontend backpressure bound (0=none)
    resource_rt: Tuple[Tuple[str, float], ...] = ()   # hot-set mean RT
    # round 20: hot-set interval p99 from the device RT histogram
    # deltas; when non-empty it supersedes resource_rt in the degrade
    # trackers (resource_rt stays as the hist-disabled fallback)
    resource_p99: Tuple[Tuple[str, float], ...] = ()


class ShedRate(NamedTuple):
    """Set the frontend admission fraction (1.0 = wide open)."""

    frac: float


class RetuneBatcher(NamedTuple):
    """Hot-swap the batcher's flush reserve and batch cap online."""

    budget_ms: int
    batch_cap: int


class Degrade(NamedTuple):
    """Force a resource's breaker: open | half_open | close."""

    resource: str
    transition: str


def action_kind(action) -> str:
    """Stable action-family name (counter / Prometheus label)."""
    return {ShedRate: "shed_rate", RetuneBatcher: "retune_batcher",
            Degrade: "degrade"}[type(action)]


class WindowedFilter:
    """BBR-style windowed extremum: the max (or min) sample over the
    trailing ``window_ms``. O(1) amortized via a monotonic deque."""

    def __init__(self, window_ms: int, mode: str = "max"):
        self.window_ms = max(1, int(window_ms))
        self._better = (lambda a, b: a >= b) if mode == "max" \
            else (lambda a, b: a <= b)
        self._q: "collections.deque[Tuple[int, float]]" = collections.deque()

    def update(self, ts_ms: int, value: float) -> float:
        q = self._q
        while q and self._better(value, q[-1][1]):
            q.pop()
        q.append((int(ts_ms), float(value)))
        while q and ts_ms - q[0][0] > self.window_ms:
            q.popleft()
        return q[0][1]

    @property
    def value(self) -> Optional[float]:
        return self._q[0][1] if self._q else None


class HistDeltaP99:
    """Interval p99 from a CUMULATIVE log-histogram bucket vector: diff
    against the previous snapshot, interpolate inside the landing bucket
    (same geometry as obs/hist.py). → p99 in ms of requests recorded
    since the last call; 0.0 when the interval recorded nothing."""

    def __init__(self) -> None:
        self._prev: Optional[List[int]] = None

    def update(self, buckets: Sequence[int]) -> float:
        cur = [int(c) for c in buckets[:NUM_BUCKETS]]
        prev = self._prev
        self._prev = cur
        if prev is None:
            delta = cur
        else:
            delta = [max(0, c - p) for c, p in zip(cur, prev)]
        total = sum(delta)
        if total == 0:
            return 0.0
        rank = max(1.0, 0.99 * total)
        cum = 0
        for i, c in enumerate(delta):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = 0 if i == 0 else (BASE_NS << (i - 1))
                hi = BASE_NS << i
                return (lo + (hi - lo) * (rank - cum) / c) / 1e6
            cum += c
        return float(BASE_NS << (NUM_BUCKETS - 1)) / 1e6  # pragma: no cover


class PolicyConfig(NamedTuple):
    """Tuning surface (mirrors the ``SENTINEL_CONTROL_*`` knobs)."""

    p99_hi_ms: float = 20.0         # backoff above this interval p99
    p99_lo_ms: float = 10.0         # recover below this; [lo,hi] = hold
    min_admit: float = 0.05         # shed floor (never black-hole)
    cooldown_ms: int = 2000         # per-action-key repeat bound
    degrade_rt_ms: float = 0.0      # per-resource RT tail bound (0 = off)
    queue_hi_frac: float = 0.75     # queue-depth overload trigger
    shed_backoff: float = 0.7       # multiplicative decrease factor
    shed_recover: float = 0.05      # additive increase step
    degrade_bad_ticks: int = 3      # consecutive bad RT ticks → open
    degrade_hold_ms: int = 5000     # open → half_open probe delay
    retune_budget_ms: int = 0       # overload flush reserve (0 = 2×base)
    retune_cap_frac: float = 0.5    # overload batch cap fraction


class _DegradeTracker:
    __slots__ = ("state", "bad", "since_ms")

    def __init__(self) -> None:
        self.state = DEG_CLOSE
        self.bad = 0
        self.since_ms = 0


class OverloadPolicy:
    """The decision core. ``observe()`` is the only entry point; it is
    deterministic in (config, observation sequence) — replaying the
    same telemetry yields the same action stream."""

    def __init__(self, cfg: PolicyConfig = PolicyConfig(), *,
                 base_budget_ms: int = 3, base_batch_cap: int = 256,
                 estimator_window_ms: int = 10_000):
        self.cfg = cfg
        self.base_budget_ms = max(0, int(base_budget_ms))
        self.base_batch_cap = max(1, int(base_batch_cap))
        self.admit_frac = 1.0
        self.degraded_batcher = False
        self.max_rate = WindowedFilter(estimator_window_ms, "max")
        self.min_rt_ms = WindowedFilter(estimator_window_ms, "min")
        self._last_ms: Dict[str, int] = {}      # action key → stamp
        self._trackers: Dict[str, _DegradeTracker] = {}

    # ---- cooldown ----------------------------------------------------

    def _ready(self, key: str, ts_ms: int) -> bool:
        last = self._last_ms.get(key)
        return last is None or ts_ms - last >= self.cfg.cooldown_ms

    def _stamp(self, key: str, ts_ms: int) -> None:
        self._last_ms[key] = ts_ms

    # ---- decision ----------------------------------------------------

    def _overload_retune(self) -> RetuneBatcher:
        budget = (self.cfg.retune_budget_ms
                  or max(1, 2 * self.base_budget_ms))
        cap = max(1, int(self.base_batch_cap * self.cfg.retune_cap_frac))
        return RetuneBatcher(budget, cap)

    def observe(self, obs: Observation) -> List:
        """One control tick: update the estimators, run the AIMD law and
        the per-resource degrade trackers; → actions to actuate (in
        emit order; may be empty — the hysteresis hold)."""
        cfg = self.cfg
        if obs.pass_per_s > 0:
            self.max_rate.update(obs.ts_ms, obs.pass_per_s)
        if obs.rt_avg_ms > 0:
            self.min_rt_ms.update(obs.ts_ms, obs.rt_avg_ms)
        actions: List = []
        queue_hot = (obs.queue_max > 0
                     and obs.queue_depth >= cfg.queue_hi_frac * obs.queue_max)
        overloaded = (obs.p99_ms > cfg.p99_hi_ms) or queue_hot
        healthy = (0.0 <= obs.p99_ms < cfg.p99_lo_ms) and not queue_hot
        if overloaded and self._ready("shed", obs.ts_ms):
            new = max(cfg.min_admit, self.admit_frac * cfg.shed_backoff)
            if new < self.admit_frac:
                self.admit_frac = new
                actions.append(ShedRate(new))
                self._stamp("shed", obs.ts_ms)
            if not self.degraded_batcher and self._ready("retune",
                                                         obs.ts_ms):
                self.degraded_batcher = True
                actions.append(self._overload_retune())
                self._stamp("retune", obs.ts_ms)
        elif healthy and self.admit_frac < 1.0 \
                and self._ready("shed", obs.ts_ms):
            new = min(1.0, self.admit_frac + cfg.shed_recover)
            self.admit_frac = new
            actions.append(ShedRate(new))
            self._stamp("shed", obs.ts_ms)
            if new >= 1.0 and self.degraded_batcher:
                # fully recovered: restore the operator's batcher tuning
                self.degraded_batcher = False
                actions.append(RetuneBatcher(self.base_budget_ms,
                                             self.base_batch_cap))
                self._stamp("retune", obs.ts_ms)
        # else: inside the [lo, hi] hysteresis band — hold
        if cfg.degrade_rt_ms > 0:
            actions.extend(self._degrade_actions(obs))
        return actions

    def _degrade_actions(self, obs: Observation) -> List[Degrade]:
        cfg = self.cfg
        out: List[Degrade] = []
        # tail-first: per-resource interval p99 when the histogram table
        # is live, hot-set mean RT otherwise (pre-r20 behavior)
        signals = obs.resource_p99 or obs.resource_rt
        for resource, rt_ms in signals:
            tr = self._trackers.get(resource)
            if tr is None:
                tr = self._trackers[resource] = _DegradeTracker()
            bad = rt_ms > cfg.degrade_rt_ms
            if tr.state == DEG_CLOSE:
                tr.bad = tr.bad + 1 if bad else 0
                if tr.bad >= cfg.degrade_bad_ticks and self._ready(
                        f"degrade:{resource}", obs.ts_ms):
                    tr.state = DEG_OPEN
                    tr.since_ms = obs.ts_ms
                    tr.bad = 0
                    out.append(Degrade(resource, DEG_OPEN))
                    self._stamp(f"degrade:{resource}", obs.ts_ms)
            elif tr.state == DEG_OPEN:
                if obs.ts_ms - tr.since_ms >= cfg.degrade_hold_ms:
                    tr.state = DEG_HALF_OPEN
                    out.append(Degrade(resource, DEG_HALF_OPEN))
            elif rt_ms > 0:                     # HALF_OPEN, probe landed
                if bad:
                    tr.state = DEG_OPEN
                    tr.since_ms = obs.ts_ms
                    out.append(Degrade(resource, DEG_OPEN))
                else:
                    tr.state = DEG_CLOSE
                    tr.bad = 0
                    out.append(Degrade(resource, DEG_CLOSE))
        return out

    # ---- read surface ------------------------------------------------

    def snapshot(self) -> Dict:
        return {
            "admit_frac": round(self.admit_frac, 4),
            "degraded_batcher": self.degraded_batcher,
            "max_rate": self.max_rate.value,
            "min_rt_ms": self.min_rt_ms.value,
            "degrade": {r: t.state for r, t in self._trackers.items()
                        if t.state != DEG_CLOSE or t.bad},
        }
