"""Action application through existing runtime-scope seams ONLY.

No new engine surface: :class:`ShedRate` rides the round-17
``IngestQueue.set_admission`` gate (requests drop BEFORE batches form,
deterministically — the drop pattern is a pure function of the seed and
arrival index, so replays shed identically);
:class:`RetuneBatcher` rides ``AdaptiveBatcher.retune`` (host-side
policy state, no retrace); :class:`Degrade` rides
``Sentinel.force_breaker`` (the device kernels evolve the forced slot
normally afterwards). Every apply returns a human-readable note — the
evidence string the loop pins into the flight recorder alongside the
triggering observation.
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.control import policy as pol
from sentinel_tpu.rules import degrade as deg_mod

_BREAKER_STATE = {
    pol.DEG_OPEN: deg_mod.STATE_OPEN,
    pol.DEG_HALF_OPEN: deg_mod.STATE_HALF_OPEN,
    pol.DEG_CLOSE: deg_mod.STATE_CLOSED,
}


class Actuators:
    """Bound to one Sentinel (+ optionally its frontend batcher).

    ``seed`` feeds the deterministic admission hash; captured once so
    every :class:`ShedRate` of a run draws from the same stream."""

    def __init__(self, sentinel, batcher=None, *, seed: int = 0):
        self._s = sentinel
        self._b = batcher
        self.seed = int(seed)

    @property
    def batcher(self):
        return self._b

    def bind_batcher(self, batcher) -> None:
        """Late-bind the frontend (it is often constructed after the
        engine); shed/retune actions are no-ops until bound."""
        self._b = batcher

    def apply(self, action) -> Optional[str]:
        """Apply one typed action; → evidence note, or None when the
        action had no seam to land on (no batcher bound / unknown
        resource) — the loop counts but does not pin those."""
        if isinstance(action, pol.ShedRate):
            b = self._b
            if b is None:
                return None
            b.queue.set_admission(action.frac, seed=self.seed)
            return f"admit_frac={action.frac:.3f} seed={self.seed}"
        if isinstance(action, pol.RetuneBatcher):
            b = self._b
            if b is None:
                return None
            b.retune(budget_ms=action.budget_ms,
                     batch_cap=action.batch_cap)
            return (f"budget_ms={b.budget_ms} "
                    f"batch_cap={b.queue.batch_max}")
        if isinstance(action, pol.Degrade):
            ok = self._s.force_breaker(
                action.resource, _BREAKER_STATE[action.transition])
            if not ok:
                return None
            return f"{action.resource}->{action.transition}"
        raise TypeError(f"unknown control action {action!r}")
