"""ControlLoop: the closed-loop runner, riding the round-16 cadence.

One host-side service with the same tick/drain discipline as telemetry
and tiering: ``tick()`` snapshots the evidence (telemetry timeline +
hot set, rolling request histogram, ingest queue depth), runs the pure
policy, and QUEUES the resulting actions; ``drain()`` applies them
through the actuators OFF the scheduler's hot path and records every
applied action — typed record in the action log, ``control.*``
counters, and a flight-recorder pin (trigger kind
``controller_action``, forced past the per-kind rate limiter: actions
are already cooldown-limited upstream, and each one must leave an
audit chain). The :class:`~sentinel_tpu.serving.CadenceScheduler`
discovers the loop via ``Sentinel.control`` and folds it into its one
daemon; ``start()`` exists for standalone use without a scheduler.

Env knobs (tune/knobs.py registry; constructor kwargs override):

* ``SENTINEL_CONTROL_DISABLE`` — kill switch: the loop never ticks and
  the admission gate stays wide open (bit-parity with pre-r17).
* ``SENTINEL_CONTROL_INTERVAL_MS`` — tick cadence, default 1000.
* ``SENTINEL_CONTROL_P99_HI_MS`` / ``_P99_LO_MS`` — the AIMD
  hysteresis band over the interval p99, defaults 20 / 10.
* ``SENTINEL_CONTROL_MIN_ADMIT`` — shed floor, default 0.05.
* ``SENTINEL_CONTROL_COOLDOWN_MS`` — per-action repeat bound, 2000.
* ``SENTINEL_CONTROL_DEGRADE_RT_MS`` — per-resource device-RT bound
  driving forced breaker transitions; 0 (default) disables the lever.
  Round 20: with the per-resource RT histogram table live, the bound
  applies to each hot resource's INTERVAL p99 (cumulative histogram
  deltas between controller ticks, obs/resource_hist.py
  ``ResourceTailTracker``) — a tail bound, which catches the
  slow-consumer pathology the old hot-set mean could never see. With
  ``SENTINEL_RESOURCE_HIST_DISABLE`` the signal falls back to the
  pre-r20 per-second mean RT.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Optional

from sentinel_tpu.control.actuators import Actuators
from sentinel_tpu.control.policy import (
    HistDeltaP99, Observation, OverloadPolicy, PolicyConfig, action_kind)
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.obs.resource_hist import ResourceTailTracker

CONTROL_DISABLE_ENV = "SENTINEL_CONTROL_DISABLE"
CONTROL_INTERVAL_ENV = "SENTINEL_CONTROL_INTERVAL_MS"
CONTROL_P99_HI_ENV = "SENTINEL_CONTROL_P99_HI_MS"
CONTROL_P99_LO_ENV = "SENTINEL_CONTROL_P99_LO_MS"
CONTROL_MIN_ADMIT_ENV = "SENTINEL_CONTROL_MIN_ADMIT"
CONTROL_COOLDOWN_ENV = "SENTINEL_CONTROL_COOLDOWN_MS"
CONTROL_DEGRADE_RT_ENV = "SENTINEL_CONTROL_DEGRADE_RT_MS"

ACTION_LOG_CAP = 256            # in-memory applied-action tail


def control_disabled() -> bool:
    return os.environ.get(CONTROL_DISABLE_ENV, "").lower() in (
        "1", "true", "on", "yes")


def _env_num(name: str, default, lo, hi, cast=float):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return min(hi, max(lo, cast(raw)))
    except ValueError:
        return default


def control_interval_ms(default: int = 1000) -> int:
    """``SENTINEL_CONTROL_INTERVAL_MS``, clamped to [50, 60000]."""
    return _env_num(CONTROL_INTERVAL_ENV, default, 50, 60_000, cast=int)


def control_p99_hi_ms(default: float = 20.0) -> float:
    """``SENTINEL_CONTROL_P99_HI_MS``, clamped to [1, 60000]."""
    return _env_num(CONTROL_P99_HI_ENV, default, 1.0, 60_000.0)


def control_p99_lo_ms(default: float = 10.0) -> float:
    """``SENTINEL_CONTROL_P99_LO_MS``, clamped to [0.5, 60000]."""
    return _env_num(CONTROL_P99_LO_ENV, default, 0.5, 60_000.0)


def control_min_admit(default: float = 0.05) -> float:
    """``SENTINEL_CONTROL_MIN_ADMIT``, clamped to [0.01, 1.0]."""
    return _env_num(CONTROL_MIN_ADMIT_ENV, default, 0.01, 1.0)


def control_cooldown_ms(default: int = 2000) -> int:
    """``SENTINEL_CONTROL_COOLDOWN_MS``, clamped to [100, 600000]."""
    return _env_num(CONTROL_COOLDOWN_ENV, default, 100, 600_000, cast=int)


def control_degrade_rt_ms(default: float = 0.0) -> float:
    """``SENTINEL_CONTROL_DEGRADE_RT_MS``, clamped to [0, 60000]."""
    return _env_num(CONTROL_DEGRADE_RT_ENV, default, 0.0, 60_000.0)


def config_from_env() -> PolicyConfig:
    """PolicyConfig off the ``SENTINEL_CONTROL_*`` knobs (bootstrap)."""
    return PolicyConfig(
        p99_hi_ms=control_p99_hi_ms(),
        p99_lo_ms=control_p99_lo_ms(),
        min_admit=control_min_admit(),
        cooldown_ms=control_cooldown_ms(),
        degrade_rt_ms=control_degrade_rt_ms(),
    )


class ControlLoop:
    """One per Sentinel; attach as ``sentinel.control`` so the serving
    scheduler folds it into its daemon (serving.py)."""

    def __init__(self, sentinel, batcher=None, *,
                 enabled: Optional[bool] = None,
                 interval_ms: Optional[int] = None,
                 config: Optional[PolicyConfig] = None,
                 seed: int = 0):
        self._s = sentinel
        self.enabled = ((not control_disabled()) if enabled is None
                        else bool(enabled))
        self.interval_ms = (control_interval_ms() if interval_ms is None
                            else max(1, int(interval_ms)))
        cfg = config_from_env() if config is None else config
        self.policy = OverloadPolicy(cfg)
        self.actuators = Actuators(sentinel, None, seed=seed)
        if batcher is not None:
            self.bind_batcher(batcher)
        self._hist_p99 = HistDeltaP99()
        self._res_tails = ResourceTailTracker()
        self._lock = threading.Lock()
        self._pending: List = []            # (Observation, actions)
        self._log: "collections.deque" = collections.deque(
            maxlen=ACTION_LOG_CAP)
        self._ticks = 0
        self.total_actions = 0
        self._last_tick_ms = sentinel.clock.now_ms()
        self._last_obs: Optional[Observation] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)
        # CadenceScheduler discovery point (serving.py): the engine's
        # ``control`` attribute IS the attachment — one loop per engine,
        # latest wins (re-attach replaces, matching rules reload idiom)
        sentinel.control = self

    def bind_batcher(self, batcher) -> None:
        """Point the shed/retune levers at a frontend batcher and adopt
        its construction-time tuning as the restore baseline."""
        self.actuators.bind_batcher(batcher)
        self.policy.base_budget_ms = batcher.budget_ms
        self.policy.base_batch_cap = batcher.batch_max

    # ---- service protocol (CadenceScheduler) -------------------------

    def last_tick_ms(self) -> int:
        return self._last_tick_ms

    def tick(self) -> int:
        """Observe + decide (cheap, host-side; safe from any thread).
        Actions queue for :meth:`drain`; → actions decided."""
        if not self.enabled or self._closed:
            return 0
        sn = self._s
        now = sn.clock.now_ms()
        self._last_tick_ms = now
        tel = sn.telemetry
        entry = None
        hot: List[Dict] = []
        if tel.enabled:
            snap = tel.snapshot(timeline_limit=1)
            timeline = snap["timeline"]
            entry = timeline[-1] if timeline else None
            hot = snap["hot"]
        pass_s = float(entry["pass"]) if entry else 0.0
        block_s = float(entry["block"]) if entry else 0.0
        succ = int(entry["success"]) if entry else 0
        rt_avg = (float(entry["rt_sum"]) / succ) if succ else 0.0
        p99 = self._hist_p99.update(
            sn.obs.hist_request.snapshot()["buckets"])
        b = self.actuators.batcher
        depth = b.pending if b is not None else 0
        qmax = b.queue.queue_max if b is not None else 0
        res_rt = ()
        res_p99 = ()
        if self.policy.cfg.degrade_rt_ms > 0:
            res_rt = tuple((h["resource"], float(h.get("rt_ms", 0.0)))
                           for h in hot if h.get("rt_ms", 0.0) > 0)
            # round 20: per-resource interval p99 from the cumulative
            # device histogram vectors the telemetry hot set carries —
            # the tail signal the degrade trackers prefer over the mean
            res_p99 = self._res_tails.update(
                (h["resource"], h["rt_hist"]) for h in hot
                if h.get("rt_hist") is not None)
        ob = Observation(now, pass_s, block_s, rt_avg, p99,
                         depth, qmax, res_rt, res_p99)
        actions = self.policy.observe(ob)
        if sn.obs.enabled:
            sn.obs.counters.add(obs_keys.CONTROL_TICK)
            if res_p99:
                sn.obs.counters.add(obs_keys.CONTROL_TAIL_SIGNAL)
        with self._lock:
            self._ticks += 1
            self._last_obs = ob
            if actions:
                self._pending.append((ob, actions))
        return len(actions)

    _ACTION_KEY = {
        "shed_rate": obs_keys.CONTROL_SHED_ACTION,
        "retune_batcher": obs_keys.CONTROL_RETUNE_ACTION,
        "degrade": obs_keys.CONTROL_DEGRADE_ACTION,
    }

    def drain(self) -> int:
        """Apply every queued action (actuators may take the engine
        lock — this runs on the scheduler thread, never inside it);
        → actions applied."""
        with self._lock:
            if not self._pending:
                return 0
            batch = self._pending
            self._pending = []
        obs_rt = self._s.obs
        applied = 0
        for ob, actions in batch:
            for action in actions:
                note = self.actuators.apply(action)
                if note is None:        # no seam bound / unknown target
                    continue
                applied += 1
                kind = action_kind(action)
                rec = {"ts_ms": ob.ts_ms, "kind": kind, "note": note,
                       "action": action._asdict(),
                       "evidence": {"p99_ms": round(ob.p99_ms, 3),
                                    "rt_avg_ms": round(ob.rt_avg_ms, 3),
                                    "queue_depth": ob.queue_depth,
                                    "pass_per_s": ob.pass_per_s,
                                    "block_per_s": ob.block_per_s}}
                with self._lock:
                    self.total_actions += 1
                    self._log.append(rec)
                if obs_rt.enabled:
                    obs_rt.counters.add(self._ACTION_KEY[kind])
                    self._pin(obs_rt, ob, kind, note)
        return applied

    def _pin(self, obs_rt, ob: Observation, kind: str, note: str) -> None:
        """Flight-recorder audit chain for one applied action: mint a
        trace carrying the evidence span, then force-pin it (an action
        must pin even when no request span landed in the window)."""
        tr = obs_rt.request_trace()
        if not tr:
            return
        t0 = obs_rt.spans.now_ns()
        obs_rt.spans.record(tr, "control.action", t0,
                            obs_rt.spans.now_ns(),
                            note=f"{kind} {note}")
        obs_rt.flight.trigger(
            "controller_action", root=tr,
            note=(f"{kind} {note} p99={ob.p99_ms:.2f}ms "
                  f"q={ob.queue_depth} pass/s={ob.pass_per_s:.0f}"),
            worst_ms=ob.p99_ms, force=True)

    def poll(self) -> int:
        """tick + drain in one call (tests / standalone daemon body)."""
        self.tick()
        return self.drain()

    # ---- read surface ------------------------------------------------

    def snapshot(self, limit: int = 32) -> Dict:
        """The ``control`` transport command / dashboard panel body."""
        with self._lock:
            ob = self._last_obs
            return {
                "enabled": self.enabled,
                "interval_ms": self.interval_ms,
                "ticks": self._ticks,
                "total_actions": self.total_actions,
                "policy": self.policy.snapshot(),
                "last_obs": None if ob is None else {
                    "ts_ms": ob.ts_ms, "p99_ms": round(ob.p99_ms, 3),
                    "rt_avg_ms": round(ob.rt_avg_ms, 3),
                    "pass_per_s": ob.pass_per_s,
                    "block_per_s": ob.block_per_s,
                    "queue_depth": ob.queue_depth,
                    "queue_max": ob.queue_max,
                },
                "actions": list(self._log)[-max(0, int(limit)):],
            }

    def action_log(self) -> List[Dict]:
        with self._lock:
            return list(self._log)

    # ---- lifecycle ---------------------------------------------------

    def start(self, interval_sec: Optional[float] = None) -> None:
        """Standalone daemon (when not riding a CadenceScheduler)."""
        if not self.enabled or self._thread is not None:
            return
        period = (self.interval_ms / 1000.0 if interval_sec is None
                  else max(0.005, float(interval_sec)))
        self._stop.clear()

        def body():
            while not self._stop.wait(period):
                try:
                    self.poll()
                except Exception:   # pragma: no cover — keep daemon alive
                    pass

        self._thread = threading.Thread(target=body, daemon=True,
                                        name="sentinel-control")
        self._thread.start()

    def stop(self) -> None:
        """Idempotent (``Sentinel.close()`` runs it via the shutdown
        registry); drops queued-but-unapplied actions — actuating into
        a closing engine would race teardown."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self._closed = True
        with self._lock:
            self._pending = []
