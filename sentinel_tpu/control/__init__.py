"""Self-driving overload protection (round 17).

A closed loop from device telemetry to the frontend: the policy core
(:mod:`~sentinel_tpu.control.policy`) turns the per-second telemetry
timeline, the rolling request-latency histogram, and the ingest queue
depth into typed actions under AIMD with hysteresis and per-action
cooldowns; the actuators (:mod:`~sentinel_tpu.control.actuators`) apply
them through existing runtime-scope seams only (frontend admission
fraction, online batcher retune, forced breaker transitions); and
:class:`~sentinel_tpu.control.loop.ControlLoop` runs the cycle on the
round-16 :class:`~sentinel_tpu.serving.CadenceScheduler` daemon,
pinning every action + its triggering evidence into the flight
recorder. See docs/OPERATIONS.md "Self-driving overload protection".
"""

from sentinel_tpu.control.policy import (           # noqa: F401
    Degrade, HistDeltaP99, Observation, OverloadPolicy, PolicyConfig,
    RetuneBatcher, ShedRate, WindowedFilter, action_kind)
from sentinel_tpu.control.actuators import Actuators  # noqa: F401
from sentinel_tpu.control.loop import (               # noqa: F401
    CONTROL_DISABLE_ENV, ControlLoop, control_disabled)
