"""``@sentinel_resource`` decorator (reference
``sentinel-extension/sentinel-annotation-aspectj/.../SentinelResourceAspect.java:36-42``
+ ``AbstractSentinelAspectSupport`` handler resolution).

Semantics mirror ``@SentinelResource``: ``block_handler`` is called on
BlockException (with the original args + the exception appended);
``fallback`` on business exceptions (unless listed in
``exceptions_to_ignore``); ``default_fallback`` takes only the exception.
Without handlers, exceptions propagate after being traced into the stats
(feeding exception-ratio breakers) — ``Tracer.traceEntry`` behavior.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional, Sequence, Tuple, Type

from sentinel_tpu.core.errors import BlockException

ENTRY_TYPE_OUT = 0
ENTRY_TYPE_IN = 1


def _invoke_handler(handler: Callable, args: tuple, kwargs: dict,
                    exc: BaseException):
    """Reference handler resolution appends the exception as the last
    positional parameter; handlers that only take the exception work too
    (defaultFallback shape)."""
    try:
        sig = inspect.signature(handler)
        n_params = len([p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)])
    except (ValueError, TypeError):
        n_params = len(args) + 1
    if n_params <= 1:
        return handler(exc)
    return handler(*args, exc, **kwargs)


def sentinel_resource(name: Optional[str] = None, *,
                      sentinel=None,
                      entry_type: int = ENTRY_TYPE_OUT,
                      resource_type: int = 0,
                      block_handler: Optional[Callable] = None,
                      fallback: Optional[Callable] = None,
                      default_fallback: Optional[Callable] = None,
                      exceptions_to_ignore: Sequence[Type[BaseException]] = (),
                      args_as_params: bool = False):
    """Guard a function as a Sentinel resource.

    ``sentinel`` may be a :class:`~sentinel_tpu.runtime.Sentinel` or a
    zero-arg callable returning one (late binding for module-level
    decoration). ``args_as_params=True`` forwards the call's positional args
    to hot-param rules (the adapter's ``SphU.entry(name, args)`` form).
    """

    def deco(fn: Callable) -> Callable:
        res_name = name or f"{fn.__module__}:{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            sph = sentinel() if callable(sentinel) else sentinel
            if sph is None:
                raise RuntimeError(
                    f"@sentinel_resource({res_name!r}): no Sentinel instance "
                    f"bound; pass sentinel=... (instance or callable)")
            try:
                e = sph.entry(res_name, entry_type=entry_type,
                              resource_type=resource_type,
                              args=args if args_as_params else ())
            except BlockException as bex:
                if block_handler is not None:
                    return _invoke_handler(block_handler, args, kwargs, bex)
                if default_fallback is not None:
                    return _invoke_handler(default_fallback, args, kwargs, bex)
                raise
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:
                ignored = isinstance(exc, tuple(exceptions_to_ignore)) \
                    if exceptions_to_ignore else False
                if not ignored:
                    e.trace(exc)     # before exit: feeds exception stats
                handler = fallback or default_fallback
                if handler is not None and not ignored \
                        and not isinstance(exc, BlockException):
                    return _invoke_handler(handler, args, kwargs, exc)
                raise
            finally:
                e.exit()

        wrapper.__sentinel_resource__ = res_name
        return wrapper

    return deco
