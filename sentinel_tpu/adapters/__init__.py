"""Integration adapters (reference ``sentinel-adapter/*``, SURVEY §2.6).

Every reference adapter reduces to the same shape: derive a resource name
(+ parse the caller origin), ``ContextUtil.enter``, ``SphU.entry``, invoke,
``Tracer.traceEntry`` on exception, ``entry.exit()``. These are the Python
ecosystem's equivalents:

- :mod:`.decorator` — ``@sentinel_resource`` with block_handler/fallback
  (``sentinel-annotation-aspectj`` ``SentinelResourceAspect``)
- :mod:`.wsgi` — WSGI middleware (``sentinel-web-servlet`` ``CommonFilter``)
- :mod:`.asgi` — ASGI 3 middleware, async (``sentinel-spring-webflux-adapter``)
- :mod:`.grpc_interceptor` — gRPC server/client interceptors
  (``sentinel-grpc-adapter``)
- :mod:`.http_client` — ``requests`` session + ``urllib`` opener guards
  (``sentinel-okhttp-adapter`` / ``sentinel-apache-httpclient-adapter``)
- :mod:`.asyncio_support` — async entry helper (``sentinel-reactor-adapter``
  ``AsyncEntry`` analog for asyncio)
- :mod:`.asgi_gateway` — gateway middleware: route + API-group resources
  with request-attribute matchers (``sentinel-spring-cloud-gateway-adapter``)
"""

from sentinel_tpu.adapters.decorator import sentinel_resource
from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware
from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
from sentinel_tpu.adapters.asyncio_support import async_entry
from sentinel_tpu.adapters.http_client import (
    SentinelAiohttpSession, SentinelSession, guarded_urlopen,
)
from sentinel_tpu.adapters.asgi_gateway import (
    AsgiRequestItemParser, SentinelGatewayASGIMiddleware,
)

__all__ = [
    "sentinel_resource", "SentinelWSGIMiddleware", "SentinelASGIMiddleware",
    "async_entry", "SentinelAiohttpSession", "SentinelSession",
    "guarded_urlopen",
    "AsgiRequestItemParser", "SentinelGatewayASGIMiddleware",
]
