"""ASGI 3 middleware (reference ``sentinel-spring-webflux-adapter`` /
``sentinel-reactor-adapter``: the async-pipeline variant of the web filter).

Same resource naming as the WSGI middleware; pacing waits are awaited with
``asyncio.sleep`` instead of blocking the event loop (the reactor adapter's
AsyncEntry pattern — the verdict carries ``wait_ms`` and the subscriber
honors it asynchronously).
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from sentinel_tpu.core.context import ContextScope
from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_WEB

from sentinel_tpu.adapters.wsgi import WEB_CONTEXT_NAME


class SentinelASGIMiddleware:
    def __init__(self, app, sentinel, *,
                 url_cleaner: Optional[Callable[[str], str]] = None,
                 origin_parser: Optional[Callable[[dict], str]] = None,
                 http_method_specify: bool = True,
                 block_status: int = 429,
                 block_body: bytes = b"Blocked by Sentinel (flow limiting)",
                 context_name: str = WEB_CONTEXT_NAME):
        self.app = app
        self.sentinel = sentinel
        self.url_cleaner = url_cleaner
        self.origin_parser = origin_parser
        self.http_method_specify = http_method_specify
        self.block_status = block_status
        self.block_body = block_body
        self.context_name = context_name

    async def _blocked(self, send) -> None:
        await send({"type": "http.response.start",
                    "status": self.block_status,
                    "headers": [(b"content-type",
                                 b"text/plain; charset=utf-8")]})
        await send({"type": "http.response.body", "body": self.block_body})

    async def __call__(self, scope, receive, send):
        if scope["type"] != "http":
            await self.app(scope, receive, send)
            return
        path = scope.get("path", "/") or "/"
        if self.url_cleaner is not None:
            path = self.url_cleaner(path)
        if not path:
            await self.app(scope, receive, send)
            return
        resource = (f"{scope.get('method', 'GET')}:{path}"
                    if self.http_method_specify else path)
        origin = (self.origin_parser(scope)
                  if self.origin_parser is not None else "")
        with ContextScope(self.context_name, origin=origin):
            try:
                entry = self.sentinel.entry(resource, entry_type=1,
                                            resource_type=TYPE_WEB,
                                            sleep=False)
            except BlockException:
                await self._blocked(send)
                return
        try:
            if entry.wait_ms > 0:   # pacing: await, don't block the loop
                await asyncio.sleep(entry.wait_ms / 1000.0)
            await self.app(scope, receive, send)
        except BaseException as exc:
            entry.trace(exc)        # incl. CancelledError on disconnect —
            entry.exit()            # the entry must not leak concurrency
            raise
        entry.exit()
