"""WSGI middleware (reference ``sentinel-web-servlet`` ``CommonFilter`` +
``WebCallbackManager``: URL cleaner, origin parser, block page).

Resource name defaults to ``METHOD:path`` (the reference's
``HttpMethodSpecify`` mode); a ``url_cleaner`` collapses dynamic segments
(``/order/123`` → ``/order/{id}``) so resource cardinality stays bounded —
the reference's ``UrlCleaner`` interface. Blocks return 429 with a plain
body by default (``DefaultBlockExceptionHandler``), customizable via
``block_handler(environ, start_response, exc)``.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from sentinel_tpu.core.context import ContextScope
from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_WEB

WEB_CONTEXT_NAME = "sentinel_web_context"   # CommonFilter WEB_CONTEXT_UNIFY


def default_block_response(environ, start_response, exc) -> Iterable[bytes]:
    body = b"Blocked by Sentinel (flow limiting)"
    start_response("429 Too Many Requests", [
        ("Content-Type", "text/plain; charset=utf-8"),
        ("Content-Length", str(len(body)))])
    return [body]


class SentinelWSGIMiddleware:
    def __init__(self, app, sentinel, *,
                 resource_extractor: Optional[Callable] = None,
                 url_cleaner: Optional[Callable[[str], str]] = None,
                 origin_parser: Optional[Callable] = None,
                 block_handler: Optional[Callable] = None,
                 http_method_specify: bool = True,
                 context_name: str = WEB_CONTEXT_NAME):
        self.app = app
        self.sentinel = sentinel
        self.resource_extractor = resource_extractor
        self.url_cleaner = url_cleaner
        self.origin_parser = origin_parser
        self.block_handler = block_handler or default_block_response
        self.http_method_specify = http_method_specify
        self.context_name = context_name

    def _resource(self, environ) -> str:
        if self.resource_extractor is not None:
            return self.resource_extractor(environ)
        path = environ.get("PATH_INFO", "/") or "/"
        if self.url_cleaner is not None:
            path = self.url_cleaner(path)
        if not path:
            return ""          # empty → pass through unguarded (reference)
        if self.http_method_specify:
            return f"{environ.get('REQUEST_METHOD', 'GET')}:{path}"
        return path

    def __call__(self, environ, start_response):
        resource = self._resource(environ)
        if not resource:
            return self.app(environ, start_response)
        origin = (self.origin_parser(environ)
                  if self.origin_parser is not None else "")
        with ContextScope(self.context_name, origin=origin):
            try:
                entry = self.sentinel.entry(resource, entry_type=1,
                                            resource_type=TYPE_WEB)
            except BlockException as exc:
                return self.block_handler(environ, start_response, exc)
            try:
                result = self.app(environ, start_response)
            except BaseException as exc:
                entry.trace(exc)
                entry.exit()
                raise
            entry.exit()
            return result
