"""aiohttp server middleware (reference ``sentinel-spring-webmvc-adapter``
``SentinelWebInterceptor`` shape, on aiohttp's middleware chain).

Usage::

    app = web.Application(middlewares=[sentinel_middleware(sph)])
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from aiohttp import web

from sentinel_tpu.core.context import ContextScope
from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_WEB

from sentinel_tpu.adapters.wsgi import WEB_CONTEXT_NAME


def sentinel_middleware(sentinel, *,
                        url_cleaner: Optional[Callable[[str], str]] = None,
                        origin_parser: Optional[Callable] = None,
                        http_method_specify: bool = True,
                        block_status: int = 429,
                        context_name: str = WEB_CONTEXT_NAME):
    @web.middleware
    async def middleware(request: web.Request, handler):
        path = request.path or "/"
        if url_cleaner is not None:
            path = url_cleaner(path)
        if not path:
            return await handler(request)
        resource = (f"{request.method}:{path}"
                    if http_method_specify else path)
        origin = origin_parser(request) if origin_parser is not None else ""
        with ContextScope(context_name, origin=origin):
            try:
                entry = sentinel.entry(resource, entry_type=1,
                                       resource_type=TYPE_WEB, sleep=False)
            except BlockException:
                return web.Response(
                    status=block_status,
                    text="Blocked by Sentinel (flow limiting)")
        try:
            if entry.wait_ms > 0:
                await asyncio.sleep(entry.wait_ms / 1000.0)
            resp = await handler(request)
        except BaseException as exc:
            entry.trace(exc)        # incl. CancelledError on disconnect
            entry.exit()
            raise
        entry.exit()
        return resp

    return middleware
