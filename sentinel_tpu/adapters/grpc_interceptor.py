"""gRPC interceptors (reference ``sentinel-grpc-adapter``:
``SentinelGrpcServerInterceptor.java:49`` / ``SentinelGrpcClientInterceptor.java:59``).

Resource = full gRPC method name (``/package.Service/Method``). The server
interceptor counts inbound entries (EntryType.IN) and aborts blocked calls
with RESOURCE_EXHAUSTED (the reference returns UNAVAILABLE-with-message; 429
maps to RESOURCE_EXHAUSTED in gRPC's status taxonomy). The client
interceptor guards outbound calls (EntryType.OUT) and traces non-OK
terminations into exception stats like the reference's
``ForwardingClientCallListener.onClose(status != OK)``.
"""

from __future__ import annotations

from typing import Callable, Optional

import grpc

from sentinel_tpu.core.context import ContextScope
from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_RPC

GRPC_CONTEXT_NAME = "sentinel_grpc_context"
BLOCK_MSG = "Blocked by Sentinel (flow limiting)"


class SentinelServerInterceptor(grpc.ServerInterceptor):
    def __init__(self, sentinel, *,
                 origin_metadata_key: str = "sentinel-origin"):
        self.sentinel = sentinel
        self.origin_metadata_key = origin_metadata_key
        self._abort = grpc.unary_unary_rpc_method_handler(self._abort_unary)

    def _abort_unary(self, request, context):
        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, BLOCK_MSG)

    def intercept_service(self, continuation, handler_call_details):
        resource = handler_call_details.method
        origin = ""
        for k, v in (handler_call_details.invocation_metadata or ()):
            if k == self.origin_metadata_key:
                origin = v if isinstance(v, str) else v.decode()
                break
        handler = continuation(handler_call_details)
        if handler is None:
            return None

        # wrap the behavior (not the dispatch) so entry/exit brackets the
        # actual method execution on the worker thread
        def wrap_unary(behavior):
            def guarded(request, context):
                with ContextScope(GRPC_CONTEXT_NAME, origin=origin):
                    try:
                        e = self.sentinel.entry(resource, entry_type=1,
                                                resource_type=TYPE_RPC)
                    except BlockException:
                        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                      BLOCK_MSG)
                    try:
                        resp = behavior(request, context)
                    except BaseException as exc:
                        e.trace(exc)
                        e.exit()
                        raise
                    e.exit()
                    return resp
            return guarded

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                wrap_unary(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)
        # streaming methods: guard the stream open; per-message flow control
        # is out of scope (matches the reference, which only wraps calls)
        return handler


class SentinelClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    def __init__(self, sentinel):
        self.sentinel = sentinel

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        resource = client_call_details.method
        if isinstance(resource, bytes):
            resource = resource.decode()
        try:
            e = self.sentinel.entry(resource, entry_type=0,
                                    resource_type=TYPE_RPC)
        except BlockException as bex:
            raise _BlockedRpcError(resource) from bex
        try:
            call = continuation(client_call_details, request)
            code = call.code()
            if code is not None and code != grpc.StatusCode.OK:
                e.trace(RuntimeError(f"grpc status {code}"))
        finally:
            e.exit()
        return call


class _BlockedRpcError(grpc.RpcError):
    def __init__(self, resource: str):
        super().__init__(f"outbound call to {resource} blocked by Sentinel")

    def code(self):
        return grpc.StatusCode.RESOURCE_EXHAUSTED

    def details(self):
        return BLOCK_MSG
