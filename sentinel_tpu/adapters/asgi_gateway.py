"""Gateway ASGI middleware (reference
``sentinel-spring-cloud-gateway-adapter``'s ``SentinelGatewayFilter``
rebuilt for Python ASGI gateways).

Per request: resolve the route resource (default: the path, override with
``route_resolver`` for real gateways with named routes), match API groups
through the :class:`~sentinel_tpu.gateway.api.GatewayApiDefinitionManager`
(`GatewayApiMatcherManager` analog), parse the gateway rules' request
attributes (IP / host / header / URL param / cookie) from the ASGI scope,
and open one entry per matched resource — route first, then API groups —
with ``resource_type`` GATEWAY. A denial answers 429 before the app runs.
"""

from __future__ import annotations

import asyncio
import urllib.parse
from typing import Callable, List, Optional, Tuple

from sentinel_tpu.core.context import ContextScope
from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_GATEWAY

WEB_CONTEXT_NAME = "sentinel_gateway_context"
RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1


class AsgiRequestItemParser:
    """``RequestItemParser`` over an ASGI http scope."""

    def get_path(self, scope) -> str:
        return scope.get("path", "/") or "/"

    def get_remote_address(self, scope) -> Optional[str]:
        client = scope.get("client")
        return client[0] if client else None

    def get_header(self, scope, key: str) -> Optional[str]:
        want = key.lower().encode("latin-1")
        for k, v in scope.get("headers", []):
            if k.lower() == want:
                return v.decode("latin-1")
        return None

    def get_url_param(self, scope, name: str) -> Optional[str]:
        qs = scope.get("query_string", b"").decode("latin-1")
        vals = urllib.parse.parse_qs(qs).get(name)
        return vals[-1] if vals else None

    def get_cookie_value(self, scope, name: str) -> Optional[str]:
        cookie = self.get_header(scope, "cookie") or ""
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == name:
                return v
        return None


class SentinelGatewayASGIMiddleware:
    def __init__(self, app, sentinel, gateway_manager,
                 api_definition_manager=None, *,
                 route_resolver: Optional[Callable[[dict], str]] = None,
                 origin_parser: Optional[Callable[[dict], str]] = None,
                 block_status: int = 429,
                 block_body: bytes = b"Blocked by Sentinel (gateway flow)",
                 context_name: str = WEB_CONTEXT_NAME):
        from sentinel_tpu.gateway.param import GatewayParamParser

        self.app = app
        self.sentinel = sentinel
        self.gateway_manager = gateway_manager
        self.api_manager = api_definition_manager
        self.route_resolver = route_resolver or (
            lambda scope: scope.get("path", "/") or "/")
        self.origin_parser = origin_parser
        self.block_status = block_status
        self.block_body = block_body
        self.context_name = context_name
        self._parser = GatewayParamParser(
            gateway_manager, item_parser=AsgiRequestItemParser())

    def _resources(self, scope) -> List[Tuple[str, int]]:
        out = [(self.route_resolver(scope), RESOURCE_MODE_ROUTE_ID)]
        if self.api_manager is not None:
            path = scope.get("path", "/") or "/"
            out.extend((name, RESOURCE_MODE_CUSTOM_API_NAME)
                       for name in self.api_manager.matching_apis(path))
        return out

    async def _blocked(self, send) -> None:
        await send({"type": "http.response.start",
                    "status": self.block_status,
                    "headers": [(b"content-type",
                                 b"text/plain; charset=utf-8")]})
        await send({"type": "http.response.body", "body": self.block_body})

    async def __call__(self, scope, receive, send):
        if scope["type"] != "http":
            await self.app(scope, receive, send)
            return
        origin = (self.origin_parser(scope)
                  if self.origin_parser is not None else "")
        entries = []
        wait_ms = 0
        with ContextScope(self.context_name, origin=origin):
            try:
                for resource, mode in self._resources(scope):
                    args = self._parser.parse_parameters(
                        resource, scope,
                        rule_predicate=lambda r, m=mode: r.resource_mode == m)
                    e = self.sentinel.entry(resource, entry_type=1,
                                            resource_type=TYPE_GATEWAY,
                                            args=tuple(args), sleep=False)
                    entries.append(e)
                    wait_ms = max(wait_ms, e.wait_ms)
            except BlockException:
                for e in reversed(entries):
                    e.exit()
                await self._blocked(send)
                return
            except BaseException:
                # non-Block failure mid-loop (a raising host gate, an
                # internal error): already-opened entries must not leak
                # concurrency
                for e in reversed(entries):
                    e.exit()
                raise
        try:
            if wait_ms > 0:         # pacing verdict: await, don't block
                await asyncio.sleep(wait_ms / 1000.0)
            await self.app(scope, receive, send)
        except BaseException as exc:
            for e in reversed(entries):
                e.trace(exc)
                e.exit()
            raise
        for e in reversed(entries):
            e.exit()
