"""Async entry helper (reference ``sentinel-reactor-adapter``
``SentinelReactorTransformer`` + ``CORE/AsyncEntry.java`` — wrap an async
operation in an entry whose pacing wait is awaited, not slept, with the
call context snapshotted for asynchronous continuation).

``async with async_entry(sph, "resource"):`` is the asyncio analog of
``try (Entry e = SphU.entry(...))``; on deny the BlockException raises out
of ``__aenter__`` before the body runs. The context (name + origin) is
captured on ``.context`` at entry time — the ``AsyncEntry`` context
snapshot — so completion work scheduled onto another task/thread can
``restore_context(ae.context)`` before making nested entries. (Plain
same-task flows don't need it: context storage is a ContextVar, private to
each asyncio task.)
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from sentinel_tpu.core.context import snapshot_context


class async_entry:
    def __init__(self, sentinel, resource: str, *,
                 origin: Optional[str] = None, acquire: int = 1,
                 entry_type: int = 1, prioritized: bool = False,
                 args: Sequence = (), resource_type: int = 0):
        self._sentinel = sentinel
        self._kw = dict(origin=origin, acquire=acquire, entry_type=entry_type,
                        prioritized=prioritized, args=args,
                        resource_type=resource_type)
        self._resource = resource
        self.entry = None
        self.context = None       # AsyncEntry context snapshot (set on enter)

    async def __aenter__(self):
        # AsyncEntry.java: snapshot the caller's context so completion code
        # running elsewhere can restore it
        self.context = snapshot_context()
        # the decide step itself is fast + non-blocking; only the pacing
        # wait must move onto the event loop
        self.entry = self._sentinel.entry(self._resource, sleep=False,
                                          **self._kw)
        if self.entry.wait_ms > 0:
            try:
                await asyncio.sleep(self.entry.wait_ms / 1000.0)
            except BaseException:
                # cancelled during the pacing wait: __aexit__ will never
                # run, so the entry must be exited here
                self.entry.exit()
                raise
        return self.entry

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.entry.trace(exc)
        self.entry.exit()
        return False
