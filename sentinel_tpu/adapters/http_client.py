"""Outbound HTTP client guards (reference ``sentinel-okhttp-adapter``
``SentinelOkHttpInterceptor`` and ``sentinel-apache-httpclient-adapter``
``SentinelApacheHttpClientExecChainHandler``).

Resource defaults to ``httpclient:METHOD:host/path-sans-query`` like the
reference's ``OkHttpResourceExtractor``; override via ``resource_extractor``.
"""

from __future__ import annotations

import urllib.parse
import urllib.request
from typing import Callable, Optional

from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_COMMON


def default_resource(method: str, url: str) -> str:
    p = urllib.parse.urlsplit(url)
    return f"httpclient:{method.upper()}:{p.netloc}{p.path}"


class SentinelSession:
    """A ``requests.Session`` subclass guarding every request.

    Defined lazily so importing this module never requires requests."""

    def __new__(cls, sentinel, *,
                resource_extractor: Optional[Callable[[str, str], str]] = None,
                **kw):
        import requests

        class _Session(requests.Session):
            def request(self, method, url, *a, **k):
                resource = (resource_extractor or default_resource)(
                    method, url)
                e = sentinel.entry(resource, entry_type=0,
                                   resource_type=TYPE_COMMON)
                try:
                    resp = super().request(method, url, *a, **k)
                except BaseException as exc:
                    e.trace(exc)
                    e.exit()
                    raise
                if resp.status_code >= 500:
                    e.trace(RuntimeError(f"http {resp.status_code}"))
                e.exit()
                return resp

        return _Session(**kw)


def guarded_urlopen(sentinel, url, *args,
                    resource_extractor: Optional[Callable] = None,
                    **kwargs):
    """stdlib variant: ``urllib.request.urlopen`` under an entry. Raises
    BlockException when denied (callers treat it like a connection error)."""
    req_url = url.full_url if isinstance(url, urllib.request.Request) else url
    method = (url.get_method()
              if isinstance(url, urllib.request.Request) else "GET")
    resource = (resource_extractor or default_resource)(method, req_url)
    e = sentinel.entry(resource, entry_type=0, resource_type=TYPE_COMMON)
    try:
        resp = urllib.request.urlopen(url, *args, **kwargs)
    except BaseException as exc:
        e.trace(exc)
        e.exit()
        raise
    e.exit()
    return resp
