"""Outbound HTTP client guards (reference ``sentinel-okhttp-adapter``
``SentinelOkHttpInterceptor``, ``sentinel-apache-httpclient-adapter``
``SentinelApacheHttpClientExecChainHandler``, and — for the async
variant — ``sentinel-spring-webflux-adapter``'s WebClient integration).

Resource defaults to ``httpclient:METHOD:host/path-sans-query`` like the
reference's ``OkHttpResourceExtractor``; override via ``resource_extractor``.
"""

from __future__ import annotations

import urllib.parse
import urllib.request
from typing import Callable, Optional

from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.metrics.node import TYPE_COMMON


def default_resource(method: str, url: str) -> str:
    p = urllib.parse.urlsplit(url)
    return f"httpclient:{method.upper()}:{p.netloc}{p.path}"


class SentinelSession:
    """A ``requests.Session`` subclass guarding every request.

    Defined lazily so importing this module never requires requests."""

    def __new__(cls, sentinel, *,
                resource_extractor: Optional[Callable[[str, str], str]] = None,
                **kw):
        import requests

        class _Session(requests.Session):
            def request(self, method, url, *a, **k):
                resource = (resource_extractor or default_resource)(
                    method, url)
                e = sentinel.entry(resource, entry_type=0,
                                   resource_type=TYPE_COMMON)
                try:
                    resp = super().request(method, url, *a, **k)
                except BaseException as exc:
                    e.trace(exc)
                    e.exit()
                    raise
                if resp.status_code >= 500:
                    e.trace(RuntimeError(f"http {resp.status_code}"))
                e.exit()
                return resp

        return _Session(**kw)


def guarded_urlopen(sentinel, url, *args,
                    resource_extractor: Optional[Callable] = None,
                    **kwargs):
    """stdlib variant: ``urllib.request.urlopen`` under an entry. Raises
    BlockException when denied (callers treat it like a connection error)."""
    req_url = url.full_url if isinstance(url, urllib.request.Request) else url
    method = (url.get_method()
              if isinstance(url, urllib.request.Request) else "GET")
    resource = (resource_extractor or default_resource)(method, req_url)
    e = sentinel.entry(resource, entry_type=0, resource_type=TYPE_COMMON)
    try:
        resp = urllib.request.urlopen(url, *args, **kwargs)
    except BaseException as exc:
        e.trace(exc)
        e.exit()
        raise
    e.exit()
    return resp


def SentinelAiohttpSession(sentinel, *,
                           resource_extractor: Optional[Callable[[str, str],
                                                                 str]] = None,
                           **kw):
    """An ``aiohttp.ClientSession`` guarding every outbound request —
    the async-client analog of :class:`SentinelSession` (reference
    ``sentinel-spring-webflux-adapter`` WebClient integration: entry
    before the exchange, block surfaces as the request's exception,
    5xx and transport errors trace into the exception stats).

    Deny raises :class:`BlockException` from the ``await``; a pacing
    wait is awaited on the event loop, never slept (the entry lifecycle
    — pacing await, cancellation safety, trace-on-exception, exit —
    rides :class:`~sentinel_tpu.adapters.asyncio_support.async_entry`).
    Defined lazily so importing this module never requires aiohttp.

    Entry-exit timing (PINNED, diverges from the WebFlux reference):
    the entry exits at HEADERS time — when ``_request`` returns the
    response object — not when the body is released/closed. RT and the
    live-concurrency gauge therefore cover connect + request + first
    response byte, excluding body streaming; the WebFlux adapter's
    ``doFinally`` covers the full exchange including the body. Rationale
    + migration notes in docs/MIGRATION.md ("aiohttp client entry
    window"); behavior pinned by
    tests/test_aiohttp_adapter.py::test_entry_exits_at_headers_time."""
    import warnings

    import aiohttp

    from sentinel_tpu.adapters.asyncio_support import async_entry

    # aiohttp deprecates ClientSession subclassing, but overriding
    # _request is the only seam that keeps the whole request API intact
    # (session.get(...) stays awaitable AND an async context manager);
    # a composition wrapper would lose that dual protocol
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)

        class _Session(aiohttp.ClientSession):
            async def _request(self, method, str_or_url, **k):
                resource = (resource_extractor or default_resource)(
                    str(method), str(str_or_url))
                async with async_entry(sentinel, resource, entry_type=0,
                                       resource_type=TYPE_COMMON) as e:
                    resp = await super()._request(method, str_or_url, **k)
                    if resp.status >= 500:
                        e.trace(RuntimeError(f"http {resp.status}"))
                    return resp

    return _Session(**kw)
