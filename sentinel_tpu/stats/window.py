"""Sliding-window counters as dense tensors — the LeapArray analog.

Reference design (``sentinel-core/.../slots/statistic/base/LeapArray.java``):
a circular array of B time buckets of length ``win`` ms; bucket index for time
t is ``(t / win) % B``; a bucket is deprecated when ``t - windowStart > B*win``
(``isWindowDeprecated``); ``currentWindow`` lazily CAS-creates/resets buckets
on touch (``LeapArray.java:128-225``); reads skip deprecated buckets
(``values()``, ``LeapArray.java:304-369``).

TPU-native rewrite: one tensor per concern instead of one LeapArray object per
resource —

* ``counters: int32[R, B, E]``  — all resources × buckets × events,
* ``stamps:   int32[R, B]``     — the *window index* (``t // win``) written last,
* ``rt_sum:   float32[R, B]``   — response-time sum (float: the ENTRY_NODE
  aggregate row would overflow int32 at high throughput),
* ``min_rt:   int32[R, B]``     — per-bucket min RT (scatter-min).

Bucket validity is purely functional and **wraparound-safe**: bucket b of row
r is live at window index ``now_idx`` iff ``0 <= now_idx - stamp < B``, with
the subtraction done in int32 two's-complement (a written stamp always
satisfies ``stamp % B == b``, so positional equality is implied). Lazy reset
becomes a branchless masked multiply *before* the scatter-add — idempotent
under duplicate rows in one batch, which is what makes batched semantics exact
(SURVEY §7 hard-part 2): all events in a device step share one ``now``, so the
reset decision is identical for every duplicate.

Time discipline (important): window indices are computed **on the host** from
exact Python ints (``WindowSpec.index_of``) and passed to device code as int32
scalars. Epoch-milliseconds never enter device arithmetic — ``epoch_ms//500``
already exceeds int32, and JAX without x64 silently truncates int64, so doing
the division device-side is a correctness trap. Device-side comparisons only
ever use int32 *differences*, which are exact as long as true gaps are under
2^31 windows (~6.8 years at the smallest 100 ms window).

All functions are pure (state in / state out) and jit-safe with static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sentinel_tpu.stats import events as ev

INT32_MAX = jnp.iinfo(jnp.int32).max
# Stamp value meaning "never written": far enough behind any real index that
# (now - stamp) is huge-positive for the first ~6.8 years, and the wraparound
# beyond that still reads as dead for any B < 2^30. A numpy (not jnp)
# scalar: materializing a device constant at import time would
# initialize the backend, which must not happen before
# jax.distributed.initialize in multi-process runs (multihost/bootstrap).
NEVER = np.int32(-(2 ** 30))


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """Static geometry (hashable → usable as a jit static arg).

    Reference defaults: the "second" window is sampleCount=2 × 500 ms
    (``SampleCountProperty``/``IntervalProperty``), the "minute" window is
    60 × 1000 ms (``StatisticNode.java:97-111``).
    """

    buckets: int
    win_ms: int
    track_rt: bool = True

    @property
    def interval_ms(self) -> int:
        return self.buckets * self.win_ms

    def index_of(self, now_ms: int) -> int:
        """HOST-side: exact window index of absolute time ``now_ms``.

        Result is reduced mod 2^32 into int32 range; all device comparisons
        are difference-based so the reduction is harmless.
        """
        idx = now_ms // self.win_ms
        return int((idx + 2 ** 31) % 2 ** 32 - 2 ** 31)


SECOND_SPEC = WindowSpec(buckets=2, win_ms=500)
# rt tracked so the metric-file pipeline can report per-second average RT
# (the reference's rollingCounterInMinute feeds MetricTimerListener)
MINUTE_SPEC = WindowSpec(buckets=60, win_ms=1000, track_rt=True)


class WindowState(NamedTuple):
    counters: jnp.ndarray          # int32[R, B, E]
    stamps: jnp.ndarray            # int32[R, B]
    rt_sum: jnp.ndarray            # float32[R, B] (or [R, 0] when untracked)
    min_rt: jnp.ndarray            # int32[R, B]   (or [R, 0] when untracked)


def init_window(spec: WindowSpec, rows: int, num_events: int = ev.NUM_EVENTS) -> WindowState:
    b_rt = spec.buckets if spec.track_rt else 0
    return WindowState(
        counters=jnp.zeros((rows, spec.buckets, num_events), jnp.int32),
        stamps=jnp.full((rows, spec.buckets), NEVER, jnp.int32),
        rt_sum=jnp.zeros((rows, b_rt), jnp.float32),
        min_rt=jnp.full((rows, b_rt), INT32_MAX, jnp.int32),
    )


def valid_mask(spec: WindowSpec, stamps: jnp.ndarray, now_idx: jnp.ndarray) -> jnp.ndarray:
    """Live-bucket mask, same shape as ``stamps`` (wraparound-safe diffs)."""
    delta = now_idx - stamps  # int32 two's-complement difference
    return (delta >= 0) & (delta < spec.buckets)


def window_sum_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                    event: int, now_idx: jnp.ndarray) -> jnp.ndarray:
    """Sum of ``event`` over live buckets for each row in ``rows`` → int32[N]."""
    sub = state.counters[rows, :, event]                 # [N, B]
    mask = valid_mask(spec, state.stamps[rows], now_idx)  # [N, B]
    return jnp.sum(jnp.where(mask, sub, 0), axis=1)


def window_sum_all(spec: WindowSpec, state: WindowState, event: int,
                   now_idx: jnp.ndarray) -> jnp.ndarray:
    """Sum of ``event`` over live buckets for every row → int32[R]."""
    mask = valid_mask(spec, state.stamps, now_idx)        # [R, B]
    return jnp.sum(jnp.where(mask, state.counters[:, :, event], 0), axis=1)


def rolling_totals(spec: WindowSpec, state: WindowState, now_idx: jnp.ndarray) -> jnp.ndarray:
    """All events, all rows → int32[R, E]; one pass for metric reporting."""
    mask = valid_mask(spec, state.stamps, now_idx)        # [R, B]
    return jnp.sum(jnp.where(mask[:, :, None], state.counters, 0), axis=1)


def rolling_load(spec: WindowSpec, state: WindowState,
                 now_idx: jnp.ndarray) -> jnp.ndarray:
    """Rolling pass+block total per row → int32[R] — the hot-resource
    ranking key of the telemetry tick (obs/telemetry.py): one masked
    sweep over two lanes instead of :func:`rolling_totals`' full event
    axis when only the ranking is needed."""
    mask = valid_mask(spec, state.stamps, now_idx)        # [R, B]
    sub = state.counters[:, :, ev.PASS] + state.counters[:, :, ev.BLOCK]
    return jnp.sum(jnp.where(mask, sub, 0), axis=1)


def rt_totals(spec: WindowSpec, state: WindowState, now_idx: jnp.ndarray) -> jnp.ndarray:
    """RT sum over live buckets for every row → float32[R]."""
    if not spec.track_rt:
        raise ValueError("rt untracked for this window spec")
    mask = valid_mask(spec, state.stamps, now_idx)
    return jnp.sum(jnp.where(mask, state.rt_sum, 0.0), axis=1)


def prev_window_sum_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                         event: int, now_idx: jnp.ndarray) -> jnp.ndarray:
    """Value of ``event`` in the *previous* window (index ``now_idx - 1``) per
    row → int32[N]. Reference: ``StatisticNode.previousPassQps`` /
    ``LeapArray.getPreviousWindow`` — zero if that bucket was never written or
    has been recycled since."""
    k = _bucket_of(spec, now_idx - 1)
    vals = state.counters[rows, k, event]
    live = state.stamps[rows, k] == (now_idx - 1)
    return jnp.where(live, vals, 0)


def refresh_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                 now_idx: jnp.ndarray) -> WindowState:
    """Lazy-reset the *current* bucket of each touched row.

    The branchless equivalent of ``LeapArray.currentWindow`` case 3
    (deprecated → tryLock + reset): where the stamp differs from ``now_idx``
    the bucket restarts from zero; multiply by {0,1} then stamp-set are both
    idempotent for duplicate rows in one batch.
    """
    k = _bucket_of(spec, now_idx)
    keep = (state.stamps[rows, k] == now_idx).astype(jnp.int32)   # [N]
    counters = state.counters.at[rows, k, :].multiply(keep[:, None], mode="drop")
    stamps = state.stamps.at[rows, k].set(now_idx, mode="drop")
    rt_sum, min_rt = state.rt_sum, state.min_rt
    if spec.track_rt:
        rt_sum = rt_sum.at[rows, k].multiply(keep.astype(jnp.float32), mode="drop")
        min_rt = min_rt.at[rows, k].set(
            jnp.where(keep == 1, state.min_rt[rows, k], INT32_MAX), mode="drop")
    return WindowState(counters, stamps, rt_sum, min_rt)


def refresh_all(spec: WindowSpec, state: WindowState,
                now_idx: jnp.ndarray) -> WindowState:
    """Lazy-reset the current bucket of EVERY row — the hot-path form of
    :func:`refresh_rows`.

    A full-table pass is a dynamic-slice update (vectorized elementwise, no
    index arrays), so at 1M rows it costs one linear sweep of
    ``counters[:, k, :]`` instead of a million-index scatter — on the TPU
    profile this replaced ~100 ms of scatter with sub-ms work per step.

    Semantics equal ``LeapArray.currentWindow(now)`` applied to all rows: at
    bucket position ``k = now_idx % B`` the only LIVE stamp is ``now_idx``
    itself (any other stamp at that position differs by a multiple of B and
    reads as dead), so zero+restamp changes no window read. Requires
    ``buckets >= 2``: with B == 1 the previous window shares the current
    bucket position, and restamping untouched rows would erase their
    ``prev_window_sum`` (warm-up's previousPassQps) — callers fall back to
    :func:`refresh_rows` there.
    """
    assert spec.buckets >= 2, "refresh_all needs B >= 2 (see docstring)"
    k = _bucket_of(spec, now_idx)
    keep = (state.stamps[:, k] == now_idx)                  # [R]
    counters = state.counters.at[:, k, :].multiply(
        keep[:, None].astype(jnp.int32))
    stamps = state.stamps.at[:, k].set(now_idx)
    rt_sum, min_rt = state.rt_sum, state.min_rt
    if spec.track_rt:
        rt_sum = rt_sum.at[:, k].multiply(keep.astype(jnp.float32))
        min_rt = min_rt.at[:, k].set(
            jnp.where(keep, state.min_rt[:, k], INT32_MAX))
    return WindowState(counters, stamps, rt_sum, min_rt)


def add_rows_vec(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                 payload: jnp.ndarray, now_idx: jnp.ndarray,
                 rt_ms: Optional[jnp.ndarray] = None,
                 rt_valid: Optional[jnp.ndarray] = None) -> WindowState:
    """Scatter-add a full event-lane vector per row: ``payload[N, E]`` lands
    in the current bucket of ``rows`` — one scatter pass where per-event
    ``add_rows`` calls would pay one pass each (an element contributing to
    several lanes, e.g. SUCCESS+EXCEPTION at exit, still costs one pass).
    Same refresh discipline and padding rules as :func:`add_rows`."""
    k = _bucket_of(spec, now_idx)
    counters = state.counters.at[rows, k, :].add(payload, mode="drop")
    rt_sum, min_rt = state.rt_sum, state.min_rt
    if spec.track_rt and rt_ms is not None:
        amt = (rt_ms if rt_valid is None
               else jnp.where(rt_valid, rt_ms, 0)).astype(jnp.float32)
        rt_sum = rt_sum.at[rows, k].add(amt, mode="drop")
        mn = (rt_ms if rt_valid is None
              else jnp.where(rt_valid, rt_ms, INT32_MAX))
        min_rt = min_rt.at[rows, k].min(mn, mode="drop")
    return WindowState(counters, state.stamps, rt_sum, min_rt)


def add_one_row(spec: WindowSpec, state: WindowState, row: int,
                vec: jnp.ndarray, now_idx: jnp.ndarray,
                rt_add: Optional[jnp.ndarray] = None,
                rt_min: Optional[jnp.ndarray] = None) -> WindowState:
    """Add a pre-reduced event vector to ONE row's current bucket.

    The global ENTRY row receives a contribution from every inbound event;
    as a scatter that doubles the index count of each recording pass — as a
    host-side reduction + this single dynamic-slice update it is one cheap
    elementwise op. Caller must have refreshed the row at ``now_idx``."""
    k = _bucket_of(spec, now_idx)
    counters = state.counters.at[row, k, :].add(vec)
    rt_sum, min_rt = state.rt_sum, state.min_rt
    if spec.track_rt and rt_add is not None:
        rt_sum = rt_sum.at[row, k].add(rt_add.astype(jnp.float32))
        if rt_min is not None:
            min_rt = min_rt.at[row, k].min(rt_min)
    return WindowState(counters, state.stamps, rt_sum, min_rt)


def _bucket_of(spec: WindowSpec, now_idx: jnp.ndarray) -> jnp.ndarray:
    # Python-style mod keeps the bucket position consistent across the int32
    # wrap for power-of-two-free B too: jnp '%' already yields non-negative
    # for positive divisor with floor semantics.
    return now_idx % spec.buckets


def add_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
             event: int, amounts: jnp.ndarray, now_idx: jnp.ndarray,
             rt_ms: Optional[jnp.ndarray] = None) -> WindowState:
    """Scatter-add ``amounts`` of ``event`` into the current bucket of ``rows``.

    Caller must have run :func:`refresh_rows` for these rows at this
    ``now_idx`` first (the pipeline refreshes once per step). Padding rows must
    use row id >= R (dropped by ``mode='drop'``); negative ids wrap in JAX and
    must not be used as padding.
    """
    k = _bucket_of(spec, now_idx)
    counters = state.counters.at[rows, k, event].add(amounts, mode="drop")
    rt_sum, min_rt = state.rt_sum, state.min_rt
    if spec.track_rt and rt_ms is not None:
        rt_sum = rt_sum.at[rows, k].add(rt_ms.astype(jnp.float32), mode="drop")
        min_rt = min_rt.at[rows, k].min(rt_ms, mode="drop")
    return WindowState(counters, state.stamps, rt_sum, min_rt)


def add_rows_multi(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                   event_ids: jnp.ndarray, amounts: jnp.ndarray,
                   now_idx: jnp.ndarray) -> WindowState:
    """Scatter-add with per-element event ids (fused multi-event record)."""
    k = _bucket_of(spec, now_idx)
    counters = state.counters.at[rows, k, event_ids].add(amounts, mode="drop")
    return state._replace(counters=counters)


def hist_add_fits(n: int, chunk: int = 1 << 15) -> bool:
    """True when an ``n``-element :func:`add_rows_hist` stays inside the
    f32-exactness bound EVEN AFTER chunk padding (the padding adds up to
    ``chunk - 1`` drop-class rows, so callers guarding on the raw ``n``
    alone can still trip the assert below). The one predicate both the
    dispatch guard (engine/pipeline.py fast-flow path) and the assert use."""
    return n + chunk <= (1 << 24)


def add_rows_hist(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                  event_ids: jnp.ndarray, amount: jnp.ndarray,
                  now_idx: jnp.ndarray, chunk: int = 1 << 15) -> WindowState:
    """:func:`add_rows_multi` for SMALL row tables with heavy index
    collisions (the alt origin/chain table): per-(row, lane) counts via a
    chunked one-hot matmul on the MXU, then ONE dense bucket-slice add —
    measured 10.1 → 3.3 ms against the colliding [2B]-index scatter at
    1M updates into 1024 rows on the v5 chip (BASELINE round-5
    continuation A/B).

    ``amount`` is the batch's single UNIFORM acquire (int32 scalar, may
    be traced): the matmul counts pure 0/1 one-hots (bf16 operands are
    exact, f32 accumulation is exact below 2^24 — asserted) and the
    scaling happens in int32 afterwards, so the result is bit-identical
    to the scatter for any uniform-acquire batch. Padding rows == R drop
    via the extra one-hot class."""
    R = state.counters.shape[0]
    n_ev = state.counters.shape[2]
    n = rows.shape[0]
    ch = min(chunk, n)
    pad = (-n) % ch          # fill the last chunk with drop-class rows —
    if pad:                  # bit-identical, and non-power-of-2 batches
        rows = jnp.concatenate(   # keep full-width matmul chunks
            [rows, jnp.full(pad, R, rows.dtype)])
        event_ids = jnp.concatenate(
            [event_ids, jnp.zeros(pad, event_ids.dtype)])
        n += pad
    assert n < (1 << 24), \
        "histogram add needs count sums exact in f32 (gate callers on " \
        "hist_add_fits(n), which accounts for this chunk padding)"

    def _chunk(carry, xs):
        r, e = xs
        oh = jax.nn.one_hot(r, R + 1, dtype=jnp.bfloat16)
        v = jax.nn.one_hot(e, n_ev, dtype=jnp.bfloat16)
        return carry + jnp.dot(oh.T, v,
                               preferred_element_type=jnp.float32), None

    delta, _ = lax.scan(
        _chunk, jnp.zeros((R + 1, n_ev), jnp.float32),
        (rows.reshape(n // ch, ch), event_ids.reshape(n // ch, ch)))
    counts = delta.astype(jnp.int32)[:R] * amount
    k = _bucket_of(spec, now_idx)
    counters = state.counters.at[:, k, :].add(counts)
    return state._replace(counters=counters)


def uncount_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                 idxs: jnp.ndarray, event: int,
                 amounts: jnp.ndarray) -> WindowState:
    """Subtract ``amounts`` of ``event`` from the bucket at window index
    ``idxs`` per row — ONLY where that bucket still carries the stamp for
    ``idxs`` (live). Reverses a reservation recorded earlier in the same
    ring lap (host lease pre-charges returning unused tokens); a rotated
    bucket already reads as zero, so no reversal is needed (or safe)
    there. Padding: rows >= R."""
    k = idxs % spec.buckets
    live = state.stamps[rows.clip(0, state.stamps.shape[0] - 1), k] == idxs
    amt = jnp.where(live, amounts, 0)
    counters = state.counters.at[rows, k, event].add(-amt, mode="drop")
    return state._replace(counters=counters)


def extract_rows(spec: WindowSpec, state: WindowState,
                 rows: jnp.ndarray) -> WindowState:
    """Gather the full window slice of each row in ``rows`` → a
    WindowState whose leading axis is ``len(rows)`` (tier demotion
    snapshot). Stamps are ABSOLUTE window indices, so the slice is
    self-contained: restored into any row at any later time it reads
    exactly as it read here (stale buckets stay stale by the validity
    arithmetic, not by position). Out-of-range rows (padding) gather
    row 0's slice — callers mask them at restore via ``mode='drop'``."""
    r = rows.clip(0, state.stamps.shape[0] - 1)
    return WindowState(counters=state.counters[r], stamps=state.stamps[r],
                       rt_sum=state.rt_sum[r], min_rt=state.min_rt[r])


def restore_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                 payload: WindowState) -> WindowState:
    """Scatter a :func:`extract_rows` payload back into ``rows`` (tier
    promotion). Overwrites the destination rows completely — the caller
    just invalidated them (registry re-allocation), so the set is exact:
    the row reads bit-identically to one that never left the device.
    Padding: rows >= R drop."""
    return WindowState(
        counters=state.counters.at[rows].set(payload.counters, mode="drop"),
        stamps=state.stamps.at[rows].set(payload.stamps, mode="drop"),
        rt_sum=state.rt_sum.at[rows].set(payload.rt_sum, mode="drop"),
        min_rt=state.min_rt.at[rows].set(payload.min_rt, mode="drop"))


def invalidate_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray) -> WindowState:
    """Forget all history of ``rows`` (registry eviction → row reuse).

    Stamps go to NEVER so every bucket reads as deprecated; counters/rt need
    no touch (refresh_rows zeroes them on next write). Without this, a row
    recycled to a new resource would inherit the evicted resource's live
    counts and could be instantly flow-blocked on another resource's traffic.
    """
    stamps = state.stamps.at[rows, :].set(NEVER, mode="drop")
    return state._replace(stamps=stamps)


def settle_occupied(spec: WindowSpec, state: WindowState,
                    occ_cnt: jnp.ndarray, occ_win: jnp.ndarray,
                    now_idx: jnp.ndarray, event: int):
    """Materialize occupy bookings into the window so the booking ring can
    be reset (rule reload rebuilds ``FlowDynState``) without forgetting
    admissions already granted.

    A LANDED booking (target window reached, still inside the rolling
    interval: ``0 <= now - w < buckets``) is credited as ``event`` counts
    into its target bucket ``w % buckets`` — every rolling sum over a span
    containing ``w`` then reads the identical total it read from the
    booking ring, so post-reload admission math is unchanged. A dead or
    rotated target bucket is fully reset (all lanes + rt) and restamped to
    ``w`` first, exactly as ``refresh_rows`` would on a write. A PENDING
    booking (``now - w == -1``: target window not reached yet) cannot land
    in a bucket that does not exist — it is returned for carry into the
    fresh booking ring instead. Anything older is expired and dropped.

    Returns ``(state', pend_cnt, pend_win)`` with the pending arrays
    shaped like the booking ring (zero / NEVER where not pending).
    """
    R = state.stamps.shape[0]
    B = spec.buckets
    rr = jnp.arange(R)
    counters, stamps = state.counters, state.stamps
    rt_sum, min_rt = state.rt_sum, state.min_rt
    pend_cnt = jnp.zeros_like(occ_cnt)
    pend_win = jnp.full_like(occ_win, NEVER)
    for s in range(occ_cnt.shape[1]):       # S = buckets + 1, static
        w = occ_win[:, s]
        c = occ_cnt[:, s]
        age = now_idx - w
        landed = (age >= 0) & (age < B) & (c > 0)
        pending = (age == -1) & (c > 0)
        k = jnp.where(landed, w % B, 0)
        live = stamps[rr, k] == w
        bsel = jnp.arange(B)[None, :] == k[:, None]          # [R, B]
        reset_rb = (landed & ~live)[:, None] & bsel
        counters = jnp.where(reset_rb[:, :, None], 0, counters)
        if spec.track_rt:
            rt_sum = jnp.where(reset_rb, 0, rt_sum)
            min_rt = jnp.where(reset_rb, INT32_MAX, min_rt)
        stamps = jnp.where(landed[:, None] & bsel, w[:, None], stamps)
        add_rb = jnp.where(landed[:, None] & bsel,
                           c.astype(jnp.int32)[:, None], 0)
        counters = counters.at[:, :, event].add(add_rb)
        pend_cnt = pend_cnt.at[:, s].set(jnp.where(pending, c, 0.0))
        pend_win = pend_win.at[:, s].set(jnp.where(pending, w, NEVER))
    state = state._replace(counters=counters, stamps=stamps)
    if spec.track_rt:
        state = state._replace(rt_sum=rt_sum, min_rt=min_rt)
    return state, pend_cnt, pend_win


def bucket_snapshot(spec: WindowSpec, state: WindowState, idx: jnp.ndarray):
    """All rows' counters (+ rt sum) for the bucket at window index ``idx`` —
    zeros where that bucket is dead. The per-second aggregation read the
    metric-file pipeline makes (``MetricTimerListener`` pulls each node's
    per-second ``metrics()``)."""
    k = _bucket_of(spec, idx)
    live = state.stamps[:, k] == idx                        # [R]
    counters = jnp.where(live[:, None], state.counters[:, k, :], 0)
    if spec.track_rt:
        rt = jnp.where(live, state.rt_sum[:, k], 0.0)
    else:
        rt = jnp.zeros(live.shape, jnp.float32)
    return counters, rt


def min_rt_rows(spec: WindowSpec, state: WindowState, rows: jnp.ndarray,
                now_idx: jnp.ndarray, default_rt: int) -> jnp.ndarray:
    """Min RT over live buckets per row (reference ``ArrayMetric.minRt`` —
    returns ``statisticMaxRt`` when nothing recorded)."""
    if not spec.track_rt:
        raise ValueError("rt untracked for this window spec")
    mask = valid_mask(spec, state.stamps[rows], now_idx)
    vals = jnp.where(mask, state.min_rt[rows], INT32_MAX)
    m = jnp.min(vals, axis=1)
    return jnp.where(m == INT32_MAX, default_rt, m).astype(jnp.int32)
