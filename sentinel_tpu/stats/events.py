"""Metric event axis for the counter tensors.

Union of the reference's per-bucket event sets:
``sentinel-core/.../slots/statistic/MetricEvent.java:21-38`` (PASS, BLOCK,
EXCEPTION, SUCCESS, OCCUPIED_PASS; RT is handled separately) and the cluster
server's ``ClusterFlowEvent`` (PASS_REQUEST/BLOCK_REQUEST/WAITING).

RT lives outside this axis: ``rt_sum`` is a float32 tensor (int32 would
overflow on the global ENTRY_NODE row: 25M events × 100ms avg per 500ms bucket
exceeds 2^31; float32 degrades gracefully for an average) and ``min_rt`` is an
int32 min-tensor (scatter-min, not scatter-add).
"""

PASS = 0
BLOCK = 1
EXCEPTION = 2
SUCCESS = 3
OCCUPIED_PASS = 4
PASS_REQUEST = 5   # cluster: number of acquire *requests* granted
BLOCK_REQUEST = 6  # cluster: number of acquire requests denied
WAITING = 7        # cluster: prioritized requests parked on future windows

NUM_EVENTS = 8

NAMES = [
    "pass", "block", "exception", "success",
    "occupied_pass", "pass_request", "block_request", "waiting",
]
