"""Spawn an N-process CPU mesh — the CI-testable multihost harness.

Real deployments start one process per host (k8s pod / MPI rank) with
the ``SENTINEL_*`` bootstrap variables set by the orchestrator. For CI
and laptops, :func:`launch` fakes the topology on one machine: N
subprocesses, each pinned to the CPU platform with
``--xla_force_host_platform_device_count`` virtual devices, rendezvous
on a coordinator port on localhost. The worker script just calls
``multihost.initialize()`` — the env contract is the same either way.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence


class LaunchError(RuntimeError):
    """A worker exited non-zero (or timed out); carries every log."""

    def __init__(self, message: str, procs: List["WorkerResult"]):
        super().__init__(message)
        self.procs = procs


@dataclasses.dataclass
class WorkerResult:
    process_id: int
    returncode: Optional[int]
    stdout: str
    stderr: str


def free_port() -> int:
    """An OS-assigned free TCP port (released before use: tiny race,
    fine for tests — the coordinator binds it back immediately)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(worker_argv: Sequence[str], num_processes: int, *,
           devices_per_process: int = 4,
           env: Optional[Dict[str, str]] = None,
           timeout_s: float = 300.0) -> List[WorkerResult]:
    """Run ``worker_argv`` as ``num_processes`` coordinated subprocesses.

    ``worker_argv`` is the python argv tail (e.g.
    ``["-m", "sentinel_tpu.multihost._parity_worker"]``); each child gets
    the bootstrap env (coordinator address, process id/count, device
    count) plus ``JAX_PLATFORMS=cpu``. Returns per-worker results once
    ALL exit cleanly; raises :class:`LaunchError` with every captured log
    otherwise (one worker dying would otherwise hang the rest on the
    collective, so failure kills the whole gang).
    """
    coord = f"127.0.0.1:{free_port()}"
    base = dict(os.environ)
    base.pop("XLA_FLAGS", None)  # parent's device forcing must not leak
    if env:
        base.update(env)
    base.update({
        "SENTINEL_COORDINATOR": coord,
        "SENTINEL_NUM_PROCESSES": str(num_processes),
        "SENTINEL_LOCAL_DEVICES": str(devices_per_process),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={devices_per_process}",
    })

    procs = []
    for pid in range(num_processes):
        child_env = dict(base)
        child_env["SENTINEL_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, *worker_argv], env=child_env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    results: List[WorkerResult] = []
    failed = False
    try:
        for pid, p in enumerate(procs):
            try:
                # once one worker died the rest are hung on collectives —
                # don't wait the full budget again for each of them
                out, err = p.communicate(
                    timeout=10.0 if failed else timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                results.append(WorkerResult(pid, None, out, err))
                failed = True
                continue
            results.append(WorkerResult(pid, p.returncode, out, err))
            failed = failed or p.returncode != 0
    finally:
        for p in procs:           # gang teardown on any failure path
            if p.poll() is None:
                p.kill()
    if failed:
        logs = "\n".join(
            f"--- worker {r.process_id} rc={r.returncode} ---\n"
            f"{r.stdout}\n{r.stderr}" for r in results)
        raise LaunchError(
            f"multihost launch of {num_processes} processes failed:\n{logs}",
            results)
    return results
