"""Multi-process bring-up: ``jax.distributed.initialize`` with guardrails.

One process per host (the JAX requirement), one of them doubling as the
coordinator. Config comes from the environment (the ``SENTINEL_*``
variables :func:`MultihostConfig.from_env` reads — what
:mod:`~sentinel_tpu.multihost.launch` exports into workers) or is built
programmatically; :func:`initialize` applies the platform switches that
MUST land before the backend spins up (CPU platform + gloo collectives —
without gloo the CPU backend refuses multi-process computations), calls
``jax.distributed.initialize``, and hands back a :class:`MultihostRuntime`
that tears everything down on ``close()``/``with``-exit.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Optional

_ENV_COORDINATOR = "SENTINEL_COORDINATOR"
_ENV_NUM_PROCESSES = "SENTINEL_NUM_PROCESSES"
_ENV_PROCESS_ID = "SENTINEL_PROCESS_ID"
_ENV_LOCAL_DEVICES = "SENTINEL_LOCAL_DEVICES"
_ENV_PLATFORM = "SENTINEL_MH_PLATFORM"

_active: Optional["MultihostRuntime"] = None


@dataclasses.dataclass(frozen=True)
class MultihostConfig:
    """Static multi-process topology for one participating process."""

    coordinator: str               # "host:port" every process can reach
    num_processes: int
    process_id: int
    local_devices: Optional[int] = None   # CPU: virtual devices per host
    platform: Optional[str] = "cpu"       # None = leave backend selection

    def __post_init__(self):
        if self.num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} outside "
                f"[0, {self.num_processes})")
        if ":" not in self.coordinator:
            raise ValueError(
                f"coordinator must be host:port, got {self.coordinator!r}")

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None,
                 ) -> "MultihostConfig":
        """Build from ``SENTINEL_COORDINATOR`` / ``SENTINEL_NUM_PROCESSES``
        / ``SENTINEL_PROCESS_ID`` (+ optional ``SENTINEL_LOCAL_DEVICES``,
        ``SENTINEL_MH_PLATFORM``) — the contract
        :func:`sentinel_tpu.multihost.launch.launch` exports to workers."""
        env = os.environ if env is None else env
        missing = [k for k in
                   (_ENV_COORDINATOR, _ENV_NUM_PROCESSES, _ENV_PROCESS_ID)
                   if not env.get(k)]
        if missing:
            raise KeyError(
                "multihost bootstrap env incomplete; missing "
                + ", ".join(missing))
        local = env.get(_ENV_LOCAL_DEVICES)
        return cls(
            coordinator=env[_ENV_COORDINATOR],
            num_processes=int(env[_ENV_NUM_PROCESSES]),
            process_id=int(env[_ENV_PROCESS_ID]),
            local_devices=int(local) if local else None,
            platform=env.get(_ENV_PLATFORM, "cpu") or None)


class MultihostRuntime:
    """Live handle for an initialized multi-process JAX runtime."""

    def __init__(self, config: MultihostConfig):
        self.config = config
        self._closed = False

    @property
    def process_index(self) -> int:
        import jax
        return jax.process_index()

    @property
    def process_count(self) -> int:
        import jax
        return jax.process_count()

    @property
    def is_coordinator(self) -> bool:
        return self.config.is_coordinator

    def local_devices(self):
        import jax
        return jax.local_devices()

    def global_devices(self):
        import jax
        return jax.devices()

    def barrier(self, name: str = "sentinel-mh") -> None:
        """Block until every process reaches the same point."""
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)

    def close(self) -> None:
        """Tear down the distributed client (idempotent)."""
        global _active
        if self._closed:
            return
        self._closed = True
        if _active is self:
            _active = None
        import jax
        if self.config.num_processes > 1:
            jax.distributed.shutdown()

    def __enter__(self) -> "MultihostRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def active_runtime() -> Optional[MultihostRuntime]:
    """The live runtime from a prior :func:`initialize`, if any."""
    return _active


def initialize(config: Optional[MultihostConfig] = None) -> MultihostRuntime:
    """Bring this process into the multi-process mesh.

    Order matters and is enforced here: platform + collective switches go
    in through ``jax.config`` BEFORE ``jax.distributed.initialize`` (the
    CPU backend only does cross-process computation with the gloo
    collectives implementation, and the switch is read at backend
    creation). ``config=None`` reads :func:`MultihostConfig.from_env`.

    Single-process configs (``num_processes == 1``) skip the distributed
    handshake entirely, so the same worker code runs 1-process reference
    jobs and N-process jobs unchanged.
    """
    global _active
    if _active is not None:
        raise RuntimeError(
            "multihost runtime already initialized; close() it first "
            "(jax.distributed supports one client per process)")
    if config is None:
        config = MultihostConfig.from_env()

    if config.local_devices:
        # only effective before the backend exists — launch.py sets it in
        # the child environment; this keeps programmatic use working too
        flag = ("--xla_force_host_platform_device_count="
                f"{config.local_devices}")
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = f"{xla_flags} {flag}".strip()

    import jax
    if config.platform:
        jax.config.update("jax_platforms", config.platform)
    if config.platform == "cpu" and config.num_processes > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    if config.num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=config.coordinator,
            num_processes=config.num_processes,
            process_id=config.process_id)

    runtime = MultihostRuntime(config)
    _active = runtime
    return runtime
