"""Global mesh construction + row-layout re-pinning across hosts.

The single-process engines build their meshes from ``jax.devices()`` of
one process; here the same call returns EVERY host's devices (ordered by
process index), so the helpers below are thin — their value is pinning
the conventions in one place:

* the cluster token engine's axis is ``"shard"`` (one device per shard,
  :mod:`sentinel_tpu.parallel.cluster`),
* the product engine's axis is ``"rows"``
  (:mod:`sentinel_tpu.parallel.local_shard`), and
* the row-sharded ``[R, B, E]`` window layouts re-pin onto the global
  mesh with a plain ``device_put`` — each process materializes only the
  shards it owns, which is exactly what host-local ingestion needs.

Geometry checks route through :mod:`sentinel_tpu.parallel.shard_math`
(the one shard-math implementation shared with both engines).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentinel_tpu.parallel import shard_math

CLUSTER_AXIS = "shard"   # parallel/cluster.py mesh axis
LOCAL_AXIS = "rows"      # parallel/local_shard.py MESH_AXIS


def global_mesh(axis: str = CLUSTER_AXIS,
                devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over every device of every participating process.

    ``jax.devices()`` already orders globally by (process, local id), so
    process *p*'s devices form one contiguous slab of the axis →
    contiguous row slabs per host, matching the
    ``row // rows_per_shard`` ownership math in ``shard_math``.
    """
    devs = np.array(jax.devices() if devices is None else list(devices))
    return Mesh(devs, (axis,))


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh crosses process boundaries (real multihost)."""
    return len({d.process_index for d in np.ravel(mesh.devices)}) > 1


def local_shard_indices(mesh: Mesh) -> List[int]:
    """Positions along the (1-D) mesh axis owned by THIS process."""
    pid = jax.process_index()
    return [i for i, d in enumerate(np.ravel(mesh.devices))
            if d.process_index == pid]


def validate_global_rows(name: str, dim: int, mesh: Mesh) -> None:
    """Row dimension must divide over the global device count."""
    shard_math.validate_divisible(name, dim, int(np.ravel(mesh.devices).size))


def row_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Shard axis 0 (rows) over the mesh axis."""
    return NamedSharding(mesh, P(axis or mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def repin_rows(tree, mesh: Mesh, axis: Optional[str] = None):
    """Re-place every leaf of a row-leading pytree (the ``[R, B, E]``
    window layouts) onto the global mesh's row sharding. Works from any
    process: ``device_put`` materializes only the locally-owned shards."""
    sh = row_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
