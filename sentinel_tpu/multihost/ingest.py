"""Host-local batch ingestion over the multi-process cluster engine.

Every process calls :meth:`MultihostIngest.request_tokens` with the SAME
request *metadata* (flow ids, acquire counts, priorities — the cheap,
shared part of the stream) for the same step; each process materializes
request *payload lanes* only for the shards its own devices hold
(``shard_math.mask_to_local_lanes`` — ``device_put`` never reads the
non-local lanes). The sharded step then runs as one SPMD program:
per-flow admission stays shard-local, the namespace request-limiter
combines with ``lax.psum``, and the verdicts come back through a
cross-process allgather — byte-identical to the single-process result
over the same stream (asserted by ``tests/test_multihost.py``).

SPMD rules the caller must keep (the engine can't check them for you):

* every process participates in every ``request_tokens`` call, in the
  same order, with the same ``now_ms``;
* rule loads / connected counts / namespace limits are replayed
  identically on every process BEFORE the step that should see them;
* the param-flow path is not wired for multihost —
  :meth:`MultihostIngest.request_params` raises ``NotImplementedError``
  at the call site (ROADMAP item 5; operational note in
  docs/OPERATIONS.md "Known multihost limitations").
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu.multihost import mesh as mh_mesh
from sentinel_tpu.parallel import shard_math
from sentinel_tpu.parallel.cluster import ClusterEngine


class MultihostIngest:
    """Drives :meth:`ClusterEngine.step_routed` collectively across hosts."""

    def __init__(self, engine: ClusterEngine):
        self.engine = engine
        self.local_shards = mh_mesh.local_shard_indices(engine.mesh)
        self.multiprocess = mh_mesh.spans_processes(engine.mesh)

    def request_tokens(self, flow_ids: Sequence[int],
                       acquire: Sequence[int],
                       prioritized: Optional[Sequence[bool]] = None,
                       *, now_ms: int) -> List[Tuple[int, int, int]]:
        """Collective ``requestToken`` step → aligned
        ``(status, wait_ms, remaining)`` per request on every process."""
        eng = self.engine
        ids = np.asarray(flow_ids)
        if ids.dtype.kind not in "iu":
            ids = np.asarray([int(f) for f in flow_ids], np.int64)
        with eng._lock:
            rowg = eng.rows_for_flows(ids)
            if rowg is None:
                # no dense lookup (sparse ids) — resolve through the dict;
                # identical on every process because rule loads are replayed
                rowg = np.asarray(
                    [eng._flow_to_row.get(int(f), -1) for f in ids],
                    np.int64)
            from sentinel_tpu.parallel.cluster import (
                STATUS_BAD_REQUEST, STATUS_FAIL, STATUS_NO_RULE_EXISTS,
            )
            lanes, plan = shard_math.route_requests(
                rowg, acquire, prioritized,
                eng.spec.n_shards, eng.spec.flows_per_shard,
                status_fail=STATUS_FAIL, status_bad=STATUS_BAD_REQUEST,
                status_no_rule=STATUS_NO_RULE_EXISTS)
            if lanes is None:
                return [(int(s), 0, 0) for s in plan.status0]
            if self.multiprocess:
                lanes = shard_math.mask_to_local_lanes(
                    lanes, plan, self.local_shards)
            verdicts = eng.step_routed(
                lanes.rows, lanes.acquire, lanes.prioritized, lanes.valid,
                lanes.lanes, now_ms=now_ms)
            return eng._gather_results_vec(verdicts, plan, lanes.lanes)

    def request_params(self, *args, **kwargs):
        """NOT wired for multihost. The param-flow step keys its table
        by host-interned param values, and those intern tables are
        process-local — routing them through the sharded step without a
        cross-process intern agreement would silently diverge per host.
        Tracked as ROADMAP item 5; single-process callers use
        ``Sentinel.entry_batch(..., args_list=...)`` directly. See
        docs/OPERATIONS.md "Known multihost limitations"."""
        raise NotImplementedError(
            "param-flow (request_params) is not wired for multihost: "
            "param intern tables are process-local and would diverge "
            "across hosts (ROADMAP item 5; docs/OPERATIONS.md 'Known "
            "multihost limitations')")
