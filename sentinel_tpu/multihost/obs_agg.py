"""Cluster-wide aggregation of per-process telemetry counters.

Each process owns its own ``Sentinel.obs`` (obs/ — per-process
:class:`~sentinel_tpu.obs.counters.CounterSet`, spans, histograms); only
the COUNTERS have a fleet-meaningful sum, and summing them is a pure
reduction over a fixed-order integer vector
(:func:`~sentinel_tpu.obs.counters.catalog_vector`: the append-only
``CATALOG`` wire format, so processes on different code revisions still
line up on the shared prefix). The collective is one
``process_allgather`` of that ``int64[len(CATALOG)]`` vector — every
process learns every other process's counts, the coordinator (or anyone)
renders totals. With one process (tests, reference jobs) the allgather
degenerates to an identity reshape, so the same code path runs 1-process
and N-process unchanged.

This is a COLLECTIVE: every process in the mesh must call
:func:`aggregate_counters` the same number of times, in the same order
relative to other collectives (the multihost SPMD rule — see
multihost/ingest.py). Never call it from only the coordinator.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from sentinel_tpu.obs import counters as obs_counters


def local_counter_vector(sentinel) -> np.ndarray:
    """This process's counters in the fixed ``CATALOG`` order
    (``int64[len(CATALOG)]``)."""
    obs = getattr(sentinel, "obs", None)
    counts = {} if obs is None else obs.counters.snapshot()
    return obs_counters.catalog_vector(counts)


def aggregate_counters(sentinel) -> Dict[str, object]:
    """Allgather + sum every process's counter vector (collective —
    call on ALL processes).

    Returns ``{"process_count", "process_index", "per_process":
    [counts...], "total": counts}`` where each ``counts`` is a
    ``{catalog key: int}`` dict (zero entries elided, matching
    ``CounterSet.snapshot``).
    """
    import jax

    local = local_counter_vector(sentinel)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        gathered = np.asarray(
            multihost_utils.process_allgather(local, tiled=False))
    else:
        gathered = local[None, :]
    gathered = gathered.reshape(-1, local.shape[0])
    per_process: List[Dict[str, int]] = [
        obs_counters.vector_counts(row) for row in gathered]
    total = obs_counters.vector_counts(gathered.sum(axis=0))
    return {
        "process_count": int(gathered.shape[0]),
        "process_index": int(jax.process_index()),
        "per_process": per_process,
        "total": total,
    }


def coordinator_report(sentinel, runtime=None) -> Optional[Dict[str, object]]:
    """:func:`aggregate_counters` (still collective — every process calls
    this), but only the coordinator gets the report back; workers get
    ``None``. ``runtime`` is an optional
    :class:`~sentinel_tpu.multihost.bootstrap.MultihostRuntime` — without
    it, coordinator-ness falls back to ``jax.process_index() == 0``."""
    agg = aggregate_counters(sentinel)
    if runtime is not None:
        is_coord = runtime.is_coordinator
    else:
        is_coord = agg["process_index"] == 0
    return agg if is_coord else None
