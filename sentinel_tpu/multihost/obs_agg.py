"""Cluster-wide aggregation of per-process telemetry counters.

Each process owns its own ``Sentinel.obs`` (obs/ — per-process
:class:`~sentinel_tpu.obs.counters.CounterSet`, spans, histograms); only
the COUNTERS have a fleet-meaningful sum, and summing them is a pure
reduction over a fixed-order integer vector
(:func:`~sentinel_tpu.obs.counters.catalog_vector`: the append-only
``CATALOG`` wire format, so processes on different code revisions still
line up on the shared prefix). The collective is one
``process_allgather`` of that ``int64[len(CATALOG)]`` vector — every
process learns every other process's counts, the coordinator (or anyone)
renders totals. With one process (tests, reference jobs) the allgather
degenerates to an identity reshape, so the same code path runs 1-process
and N-process unchanged.

:func:`aggregate_topk` does the same for the hot-resource telemetry
layer (obs/telemetry.py): each host's top-K rides one fixed-shape
allgather (padded utf-8 names + int64 load/pass/block) and merges by
resource name into a cluster-wide hot view — the first concrete piece of
the ROADMAP cluster health view.

:func:`aggregate_resource_hist` extends that merge to the per-resource
RT histogram table (obs/resource_hist.py): cumulative log-bucket count
vectors are pure sums, so summing each resource's vector across hosts
and re-extracting quantiles host-side yields the FLEET-WIDE tail — the
true cluster p99, not a mean of per-host p99s (quantiles don't average;
histograms do).

These are COLLECTIVES: every process in the mesh must call them the same
number of times, in the same order relative to other collectives (the
multihost SPMD rule — see multihost/ingest.py). Never call them from
only the coordinator.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from sentinel_tpu.obs import counters as obs_counters


def local_counter_vector(sentinel) -> np.ndarray:
    """This process's counters in the fixed ``CATALOG`` order
    (``int64[len(CATALOG)]``)."""
    obs = getattr(sentinel, "obs", None)
    counts = {} if obs is None else obs.counters.snapshot()
    return obs_counters.catalog_vector(counts)


def aggregate_counters(sentinel) -> Dict[str, object]:
    """Allgather + sum every process's counter vector (collective —
    call on ALL processes).

    Returns ``{"process_count", "process_index", "per_process":
    [counts...], "total": counts}`` where each ``counts`` is a
    ``{catalog key: int}`` dict (zero entries elided, matching
    ``CounterSet.snapshot``).
    """
    import jax

    local = local_counter_vector(sentinel)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        gathered = np.asarray(
            multihost_utils.process_allgather(local, tiled=False))
    else:
        gathered = local[None, :]
    gathered = gathered.reshape(-1, local.shape[0])
    per_process: List[Dict[str, int]] = [
        obs_counters.vector_counts(row) for row in gathered]
    total = obs_counters.vector_counts(gathered.sum(axis=0))
    return {
        "process_count": int(gathered.shape[0]),
        "process_index": int(jax.process_index()),
        "per_process": per_process,
        "total": total,
    }


#: Fixed per-entry name payload of the top-K allgather (utf-8, truncated
#: — wire format like CATALOG: changing it breaks cross-revision merges).
TOPK_NAME_BYTES = 64


def _topk_payload(sentinel, k: int):
    """This process's hot set as fixed-shape allgather payload:
    ``(uint8[k, TOPK_NAME_BYTES] names, int64[k, 3] load/pass/block)``,
    empty slots marked by load == -1."""
    names = np.zeros((k, TOPK_NAME_BYTES), np.uint8)
    stats = np.full((k, 3), -1, np.int64)
    telemetry = getattr(sentinel, "telemetry", None)
    entries = telemetry.hot_entries(k) if telemetry is not None else []
    for i, h in enumerate(entries[:k]):
        raw = h["resource"].encode("utf-8")[:TOPK_NAME_BYTES]
        names[i, :len(raw)] = np.frombuffer(raw, np.uint8)
        stats[i] = (h["load"], h["pass"], h["block"])
    return names, stats


def aggregate_topk(sentinel, k: Optional[int] = None) -> Dict[str, object]:
    """Allgather-merge every host's hot-resource top-K into ONE
    cluster-wide hot view (collective — call on ALL processes, with the
    same ``k``; defaults to this engine's ``telemetry.k``, which matches
    fleet-wide when the knob env is uniform).

    Per-host engines are independent (each serves its own traffic), so
    the cluster view sums load/pass/block per resource NAME across hosts
    and re-ranks — a resource hot on several hosts outranks one spiking
    on a single host. Returns ``{"process_count", "process_index", "k",
    "hot": [{resource, load, pass, block, hosts}, ...]}`` (top-k,
    identical on every process)."""
    import jax

    telemetry = getattr(sentinel, "telemetry", None)
    if k is None:
        k = telemetry.k if telemetry is not None else 16
    k = max(1, int(k))
    names, stats = _topk_payload(sentinel, k)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        g_names = np.asarray(multihost_utils.process_allgather(
            names, tiled=False)).reshape(-1, k, TOPK_NAME_BYTES)
        g_stats = np.asarray(multihost_utils.process_allgather(
            stats, tiled=False)).reshape(-1, k, 3)
    else:
        g_names, g_stats = names[None], stats[None]
    merged: Dict[str, List[int]] = {}
    hosts: Dict[str, int] = {}
    for p in range(g_stats.shape[0]):
        for i in range(k):
            load = int(g_stats[p, i, 0])
            if load < 0:
                continue
            raw = bytes(g_names[p, i]).rstrip(b"\x00")
            name = raw.decode("utf-8", errors="replace")
            cur = merged.setdefault(name, [0, 0, 0])
            cur[0] += load
            cur[1] += int(g_stats[p, i, 1])
            cur[2] += int(g_stats[p, i, 2])
            hosts[name] = hosts.get(name, 0) + 1
    ranked = sorted(merged.items(), key=lambda it: (-it[1][0], it[0]))[:k]
    return {
        "process_count": int(g_stats.shape[0]),
        "process_index": int(jax.process_index()),
        "k": k,
        "hot": [{"resource": name, "load": s[0], "pass": s[1],
                 "block": s[2], "hosts": hosts[name]}
                for name, s in ranked],
    }


def _resource_hist_payload(sentinel, k: int, hb: int):
    """This process's hot set + histogram rows as fixed-shape allgather
    payload: ``(uint8[k, TOPK_NAME_BYTES] names, int64[k, hb] counts)``,
    empty slots marked by ``counts[i, 0] == -1`` (real bucket counts are
    never negative)."""
    names = np.zeros((k, TOPK_NAME_BYTES), np.uint8)
    hists = np.full((k, hb), -1, np.int64)
    telemetry = getattr(sentinel, "telemetry", None)
    entries = telemetry.hot_entries(k) if telemetry is not None else []
    for i, h in enumerate(entries[:k]):
        vec = h.get("rt_hist")
        if vec is None or len(vec) != hb:
            continue
        raw = h["resource"].encode("utf-8")[:TOPK_NAME_BYTES]
        names[i, :len(raw)] = np.frombuffer(raw, np.uint8)
        hists[i] = np.asarray(vec, np.int64)
    return names, hists


def aggregate_resource_hist(sentinel,
                            k: Optional[int] = None) -> Dict[str, object]:
    """Allgather-merge every host's per-resource RT histogram rows into
    ONE fleet-wide tail view (collective — call on ALL processes, with
    the same ``k``; the bucket count comes from this engine's spec and
    must be fleet-uniform, which SENTINEL_RESOURCE_HIST_BUCKETS being a
    uniform env guarantees).

    Cumulative bucket counts sum exactly across hosts (the same merge
    the row-shard gather does device-side in obs/telemetry.py), so the
    quantiles extracted from the summed vectors are the TRUE fleet
    quantiles. Returns ``{"process_count", "process_index", "k",
    "hist_buckets", "hot": [{resource, total, hosts, rt_hist,
    rt_p50_ms, rt_p95_ms, rt_p99_ms}, ...]}`` ranked by total count
    (identical on every process). Empty when the histogram table is
    disabled (``hist_buckets == 0``)."""
    import jax

    from sentinel_tpu.obs import resource_hist

    telemetry = getattr(sentinel, "telemetry", None)
    if k is None:
        k = telemetry.k if telemetry is not None else 16
    k = max(1, int(k))
    spec = getattr(sentinel, "spec", None)
    hb = int(getattr(spec, "hist_buckets", 0) or 0)
    if hb <= 0:
        return {"process_count": int(jax.process_count()),
                "process_index": int(jax.process_index()),
                "k": k, "hist_buckets": 0, "hot": []}
    names, hists = _resource_hist_payload(sentinel, k, hb)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        g_names = np.asarray(multihost_utils.process_allgather(
            names, tiled=False)).reshape(-1, k, TOPK_NAME_BYTES)
        g_hists = np.asarray(multihost_utils.process_allgather(
            hists, tiled=False)).reshape(-1, k, hb)
    else:
        g_names, g_hists = names[None], hists[None]
    merged: Dict[str, np.ndarray] = {}
    hosts: Dict[str, int] = {}
    for p in range(g_hists.shape[0]):
        for i in range(k):
            if g_hists[p, i, 0] < 0:
                continue
            raw = bytes(g_names[p, i]).rstrip(b"\x00")
            name = raw.decode("utf-8", errors="replace")
            if name in merged:
                merged[name] = merged[name] + g_hists[p, i]
            else:
                merged[name] = g_hists[p, i].copy()
            hosts[name] = hosts.get(name, 0) + 1
    ranked = sorted(merged.items(),
                    key=lambda it: (-int(it[1].sum()), it[0]))[:k]
    hot = []
    for name, vec in ranked:
        qs = resource_hist.np_quantiles(vec.astype(np.int64))
        hot.append({
            "resource": name,
            "total": int(vec.sum()),
            "hosts": hosts[name],
            "rt_hist": [int(c) for c in vec],
            "rt_p50_ms": round(float(qs[0]), 3),
            "rt_p95_ms": round(float(qs[1]), 3),
            "rt_p99_ms": round(float(qs[2]), 3),
        })
    return {
        "process_count": int(g_hists.shape[0]),
        "process_index": int(jax.process_index()),
        "k": k,
        "hist_buckets": hb,
        "hot": hot,
    }


def coordinator_report(sentinel, runtime=None) -> Optional[Dict[str, object]]:
    """:func:`aggregate_counters` (still collective — every process calls
    this), but only the coordinator gets the report back; workers get
    ``None``. ``runtime`` is an optional
    :class:`~sentinel_tpu.multihost.bootstrap.MultihostRuntime` — without
    it, coordinator-ness falls back to ``jax.process_index() == 0``."""
    agg = aggregate_counters(sentinel)
    if runtime is not None:
        is_coord = runtime.is_coordinator
    else:
        is_coord = agg["process_index"] == 0
    return agg if is_coord else None
