"""Worker for the cluster-wide hot-view merge test (multihost/obs_agg.py
``aggregate_topk``).

Spawned by :func:`sentinel_tpu.multihost.launch.launch`. Each process
builds its OWN local engine (independent per-host engines — the ROADMAP
cluster-health-view topology, not the row-sharded SPMD engine), drives a
deterministic per-process traffic mix with one process-specific hot key
plus one key hot on EVERY host, runs one telemetry poll, and joins the
collective top-K allgather. The coordinator prints one
``TOPK_JSON:``-prefixed line with the merged hot view —
``tests/test_multihost.py`` asserts the per-host keys surface and the
shared key's load is the cross-host sum.
"""

from __future__ import annotations

import json
import sys

NOW0 = 10_000_000
HOT_N = 30        # per-process hot key entries
SHARED_N = 20     # entries every process sends to the shared key
COLD_N = 2
TOPK_K = 8


def main(argv) -> int:
    import sentinel_tpu as stpu
    from sentinel_tpu import multihost
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.core.errors import BlockException
    from sentinel_tpu.multihost.obs_agg import aggregate_topk

    with multihost.initialize() as rt:
        p = rt.process_index
        cfg = stpu.load_config(max_resources=64, max_flow_rules=16,
                               max_degrade_rules=16, host_fast_path=False)
        s = stpu.Sentinel(cfg, clock=ManualClock(start_ms=NOW0))
        for name, n in ((f"hot-{p}", HOT_N), ("shared-hot", SHARED_N),
                        (f"cold-{p}", COLD_N)):
            for _ in range(n):
                try:
                    s.entry(name).exit()
                except BlockException:   # rule-free: never taken
                    pass
        s.clock.advance_ms(50)
        s.telemetry.poll()
        agg = aggregate_topk(s, k=TOPK_K)
        agg["local_hot"] = s.telemetry.hot_entries()
        if p == 0:
            print("TOPK_JSON:" + json.dumps(agg), flush=True)
        rt.barrier("topk-done")
        s.close()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
