"""Multi-host mesh runtime: real multi-process operation for the engines.

Takes the product engine (:mod:`sentinel_tpu.runtime` over a ``"rows"``
mesh) and the cluster token engine
(:mod:`sentinel_tpu.parallel.cluster`) from single-process virtual
meshes to a coordinator-bootstrapped multi-process mesh — the reference
system's own deployment shape (N processes speaking to shared state),
rebuilt as one SPMD program spanning hosts.

Pieces:

* :mod:`~sentinel_tpu.multihost.bootstrap` — ``jax.distributed``
  bring-up/teardown from env vars or programmatic config;
* :mod:`~sentinel_tpu.multihost.mesh` — the global mesh over every
  host's local devices, plus row-layout re-pinning helpers;
* :mod:`~sentinel_tpu.multihost.ingest` — host-local batch ingestion
  driving :meth:`ClusterEngine.step_routed` collectively;
* :mod:`~sentinel_tpu.multihost.launch` — N-process CPU-mesh spawner so
  all of the above is testable in CI without TPUs;
* :mod:`~sentinel_tpu.multihost.obs_agg` — collective allgather + sum of
  each process's telemetry counters (obs/) at the coordinator.
"""

from sentinel_tpu.multihost.bootstrap import (
    MultihostConfig, MultihostRuntime, active_runtime, initialize,
)
from sentinel_tpu.multihost.ingest import MultihostIngest
from sentinel_tpu.multihost.launch import LaunchError, free_port, launch
from sentinel_tpu.multihost import mesh
from sentinel_tpu.multihost.obs_agg import (
    aggregate_counters, coordinator_report,
)

__all__ = [
    "MultihostConfig", "MultihostRuntime", "MultihostIngest",
    "LaunchError", "active_runtime", "aggregate_counters",
    "coordinator_report", "free_port", "initialize", "launch", "mesh",
]
