"""Worker for multihost tests/benchmarks: deterministic token streams.

Spawned by :func:`sentinel_tpu.multihost.launch.launch` (any process
count — the same script is the 1-process reference and the N-process
subject). Bootstraps from env, builds the global mesh + cluster engine,
replays a fixed rule set and a seeded request stream through
:class:`MultihostIngest`, and prints one ``PARITY_JSON:``-prefixed line
from the coordinator with every decision — the byte-identical payload
``tests/test_multihost.py`` compares across process counts.

``--bench`` switches to a throughput loop (same engine, bigger batches)
and emits ``BENCH_JSON:`` instead — consumed by
``benchmarks/multihost_bench.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

NOW0 = 10_000_000
SEED = 0xC1A0
N_FLOWS = 24
FLOW0 = 100


def build_engine():
    from sentinel_tpu.multihost import mesh as mh_mesh
    from sentinel_tpu.parallel.cluster import (
        THRESHOLD_AVG_LOCAL, THRESHOLD_GLOBAL, ClusterEngine,
        ClusterFlowRule, ClusterSpec,
    )
    mesh = mh_mesh.global_mesh()
    n_dev = mesh.devices.size
    spec = ClusterSpec(n_shards=n_dev, flows_per_shard=16, namespaces=4)
    engine = ClusterEngine(spec, mesh=mesh)

    # identical replay on every process (SPMD requirement)
    rules_a = [ClusterFlowRule(
        flow_id=FLOW0 + i, count=3 + (i % 5),
        threshold_type=(THRESHOLD_AVG_LOCAL if i % 4 == 0
                        else THRESHOLD_GLOBAL))
        for i in range(N_FLOWS // 2)]
    rules_b = [ClusterFlowRule(
        flow_id=FLOW0 + i, count=4 + (i % 7), threshold_type=THRESHOLD_GLOBAL)
        for i in range(N_FLOWS // 2, N_FLOWS)]
    engine.load_rules("ns-a", rules_a)
    engine.load_rules("ns-b", rules_b)
    engine.set_connected_count("ns-a", 3)
    engine.set_namespace_qps_limit("ns-b", 40)
    return engine


def stream(batches: int, batch: int):
    """Seeded request stream, independent of topology."""
    rng = np.random.RandomState(SEED)
    for t in range(batches):
        ids = rng.randint(FLOW0 - 2, FLOW0 + N_FLOWS + 2, size=batch)
        acq = rng.randint(-1, 4, size=batch)   # includes bad requests
        prio = rng.rand(batch) < 0.25
        yield ids, acq, prio, NOW0 + t * 137
    # and one batch a window later: slide/replenish must agree too
    ids = rng.randint(FLOW0, FLOW0 + N_FLOWS, size=batch)
    yield ids, np.ones(batch, np.int64), np.zeros(batch, np.bool_), \
        NOW0 + 2_000


def run_parity(ingest) -> dict:
    out = []
    for ids, acq, prio, now in stream(batches=6, batch=64):
        out.extend(list(map(list, ingest.request_tokens(
            ids, acq, prio, now_ms=now))))
    return {"decisions": out}


def run_bench(ingest, batches: int = 0, batch: int = 0) -> dict:
    batch = batch or int(os.environ.get("MH_BENCH_BATCH", "512"))
    batches = batches or int(os.environ.get("MH_BENCH_BATCHES", "40"))
    # warmup: trigger every compile outside the timed region
    for ids, acq, prio, now in stream(batches=2, batch=batch):
        ingest.request_tokens(ids, acq, prio, now_ms=now)
    t0 = time.perf_counter()
    n = 0
    for t in range(batches):
        ids = np.arange(batch, dtype=np.int64) % N_FLOWS + FLOW0
        acq = np.ones(batch, np.int64)
        ingest.request_tokens(ids, acq, None,
                              now_ms=NOW0 + 10_000 + t * 97)
        n += batch
    dt = time.perf_counter() - t0
    return {"requests": n, "elapsed_s": dt, "rps": n / dt,
            "batch": batch, "batches": batches}


def main(argv) -> int:
    from sentinel_tpu import multihost

    bench = "--bench" in argv
    with multihost.initialize() as rt:
        engine = build_engine()
        ingest = multihost.MultihostIngest(engine)
        payload = run_bench(ingest) if bench else run_parity(ingest)
        payload.update(
            process_count=rt.process_count,
            n_devices=len(rt.global_devices()),
            local_shards=list(ingest.local_shards))
        if rt.process_index == 0:
            tag = "BENCH_JSON:" if bench else "PARITY_JSON:"
            print(tag + json.dumps(payload), flush=True)
        rt.barrier("parity-done")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
