"""Static API facade (reference ``SphU`` / ``SphO`` / ``Tracer`` — the
global-singleton entry points most Sentinel code uses).

The class-based API (:class:`~sentinel_tpu.runtime.Sentinel`) is the primary
surface; this module provides the reference's static-facade ergonomics over a
process-wide default instance::

    import sentinel_tpu.api as sph

    sph.init(stpu.load_config())              # optional; lazy default else
    with sph.entry("HelloWorld"):             # SphU.entry
        ...
    if sph.try_entry("maybe"):                # SphO.entry (boolean, no raise)
        try: ...
        finally: sph.exit()

``Tracer``-style exception reporting: ``sph.trace(exc)`` marks the current
innermost entry (reference ``Tracer.trace`` walks ``context.curEntry``).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from sentinel_tpu.core.errors import BlockException
from sentinel_tpu.core.initexec import InitExecutor
from sentinel_tpu.runtime import ENTRY_TYPE_IN, Entry, Sentinel

_lock = threading.Lock()
_instance: Optional[Sentinel] = None
_generation = 0           # bumped by init/reset; invalidates old tls stacks
_tls = threading.local()


def init(config=None, **kw) -> Sentinel:
    """Install the process-wide instance (reference ``Env`` static init);
    idempotent unless a config is passed. Runs registered InitFunc SPI
    hooks once per process (``InitExecutor.doInit``)."""
    global _instance, _generation
    with _lock:
        if _instance is None or config is not None or kw:
            _instance = Sentinel(config, **kw)
            _generation += 1
        inst = _instance
    InitExecutor.do_init(inst)
    return inst


def instance() -> Sentinel:
    global _instance
    if _instance is None:
        with _lock:
            if _instance is None:
                _instance = Sentinel()
    # Always rendezvous with InitExecutor: if another thread is mid-init,
    # this blocks until its hooks complete, so no caller can use the
    # instance before "hooks run before first use" holds. Steady state is
    # one lock-free Event.is_set() check.
    InitExecutor.do_init(_instance)
    return _instance


def reset() -> None:
    """Drop the global instance (test hygiene — ``ContextTestUtil`` analog).
    Every thread's entry stack is invalidated: entries opened against the
    dead instance are no longer addressable through this facade."""
    global _instance, _generation
    with _lock:
        _instance = None
        _generation += 1


def _stack():
    # stacks are tied to the instance generation they were opened under so
    # reset()/re-init can't route exits into a discarded instance
    if getattr(_tls, "generation", None) != _generation:
        _tls.generation = _generation
        _tls.entries = []
    return _tls.entries


def entry(resource: str, **kw) -> Entry:
    """``SphU.entry`` — raises BlockException when denied. The returned Entry
    is also pushed on a per-thread stack so ``exit()``/``trace()`` can find
    it (reference ``context.curEntry`` chain)."""
    e = instance().entry(resource, **kw)
    st = _stack()
    st.append(e)

    def _pop(done: Entry) -> None:
        # pop on exit regardless of which exit path ran; mispaired exits
        # just remove their own entry (ErrorEntryFree semantics are already
        # enforced by Entry.exit's double-exit check)
        if st and st[-1] is done:
            st.pop()
        elif done in st:
            st.remove(done)

    e.when_terminate(_pop)
    return e


def try_entry(resource: str, **kw) -> bool:
    """``SphO.entry`` — boolean, never raises; pair with ``exit()``."""
    try:
        entry(resource, **kw)
        return True
    except BlockException:
        return False


def exit(n: int = 1) -> None:           # noqa: A001 (reference name)
    """``SphO.exit``/``Entry.exit`` for the innermost ``n`` entries."""
    st = _stack()
    for _ in range(min(n, len(st))):
        st[-1].exit()


# Tracer exception-class filters (reference ``Tracer.setExceptionsToTrace``
# / ``setExceptionsToIgnore``; ignore wins on overlap)
_trace_classes: tuple = (Exception,)
_ignore_classes: tuple = ()


def set_exceptions_to_trace(*classes) -> None:
    """Only these exception classes (and subclasses) count toward
    exception stats/breakers via :func:`trace` (``Tracer.java:96``)."""
    global _trace_classes
    _trace_classes = tuple(classes) or (Exception,)


def set_exceptions_to_ignore(*classes) -> None:
    """These classes never count, even if listed in the trace set
    (``Tracer.java:117``; ignore takes precedence)."""
    global _ignore_classes
    _ignore_classes = tuple(classes)


def should_trace(exc: BaseException) -> bool:
    return (exc is not None
            and not isinstance(exc, _ignore_classes or ())
            and isinstance(exc, _trace_classes))


def trace(exc: BaseException) -> None:
    """``Tracer.trace`` — record a business exception on the innermost
    in-flight entry of this thread, honoring the class filters."""
    if not should_trace(exc):
        return
    st = _stack()
    if st:
        st[-1].trace(exc)


def trace_entry(exc: BaseException, entry_obj: Entry) -> None:
    """``Tracer.traceEntry`` — record on an explicit entry."""
    if entry_obj is not None and should_trace(exc):
        entry_obj.trace(exc)


def current_entry() -> Optional[Entry]:
    st = _stack()
    return st[-1] if st else None
