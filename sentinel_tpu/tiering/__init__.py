"""Tiered resource state (round 15): device hot tier + host cold tier.

Every dispatch path used to assume the whole keyspace fits the pre-sized
device table (ROADMAP item 2's scaling wall). This package breaks that:
the existing sharded ``WindowState`` rows are the HOT tier (hot-path
math unchanged), evicted rows' window counters, occupy bookings and
thread gauges spill to a host-memory COLD tier
(:class:`~sentinel_tpu.tiering.coldtier.ColdTier`), and a re-interned
cold key is promoted back bit-identically
(:class:`~sentinel_tpu.tiering.manager.TierManager`) — total key
cardinality is unbounded while the device table stays fixed-size.

Hot-set discovery runs on-device: a conservative-update count-min
sketch (:mod:`~sentinel_tpu.tiering.sketch`) is updated from each
batch's resource rows under the engine lock (dispatch-only, no sync),
and the tiering ticker thread — modeled on the round-12 telemetry
ticker — drains estimates asynchronously and proactively demotes
low-estimate rows so LRU pressure never lands on a hot row.

See docs/OPERATIONS.md "Tiered resource state (round 15)" for the
operational runbook and the slow-path caveat.
"""

from sentinel_tpu.tiering.coldtier import ColdEntry, ColdTier
from sentinel_tpu.tiering.manager import (
    HOT_ROWS_ENV, SKETCH_BITS_ENV, SKETCH_ROWS_ENV, TIER_TICK_MS_ENV,
    TIERING_DISABLE_ENV, TierManager, tier_hot_rows, tier_sketch_bits,
    tier_sketch_rows, tier_tick_ms, tiering_disabled,
)
from sentinel_tpu.tiering.sketch import (
    SKETCH_IMPLS, decay_sketch, estimate_all, init_sketch, update_sketch,
)

__all__ = [
    "ColdEntry", "ColdTier", "TierManager",
    "HOT_ROWS_ENV", "SKETCH_BITS_ENV", "SKETCH_ROWS_ENV",
    "TIER_TICK_MS_ENV", "TIERING_DISABLE_ENV",
    "tier_hot_rows", "tier_sketch_bits", "tier_sketch_rows",
    "tier_tick_ms", "tiering_disabled",
    "SKETCH_IMPLS", "init_sketch", "update_sketch", "decay_sketch",
    "estimate_all",
]
