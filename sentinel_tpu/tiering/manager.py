"""TierManager: the hot/cold state machine around the device row table.

Lifecycle of a key under tiering (default ON; ``SENTINEL_TIERING_DISABLE``
reverts to the pre-round-15 lossy eviction):

* **resident (hot)** — a registry row; the dispatch paths are unchanged.
* **demotion** — when the registry recycles a row (LRU overflow, or the
  ticker's proactive ``evict_name``), the engine's eviction drain FIRST
  dispatches a jitted gather of the row's complete state
  (``engine.pipeline.extract_resource_rows`` — fresh output buffers,
  dispatch-only under the engine lock) and queues it; the tiering
  thread lands it into the :class:`~sentinel_tpu.tiering.coldtier.ColdTier`
  off-lock. THEN the usual invalidate runs. ``tier.demoted`` ticks.
* **cold** — host memory only; unbounded cardinality.
* **promotion (the documented slow path)** — when a cold key is
  interned again, the NEXT eviction drain (which runs under the engine
  lock before every decide) scatters the cold payload back into the
  freshly allocated row (``restore_resource_rows``), after replaying
  any flow-rule reloads the key slept through
  (:func:`~sentinel_tpu.tiering.coldtier.settle_entry_np`). The decide
  that triggered the intern therefore sees the row EXACTLY as if it had
  never left the device — verdict bit-parity is by construction
  (window stamps and booking windows are absolute indices, so the
  payload is time-portable), at the cost of one synchronous
  host→device scatter on that batch (``tier.cold_miss`` +
  ``tier.promoted`` tick; latency lands in
  :attr:`TierManager.migration_hist`).

Hot-set discovery: a conservative-update count-min sketch
(:mod:`~sentinel_tpu.tiering.sketch`) over the batch's resource rows,
updated under the engine lock inside the decide paths (dispatch-only).
The ticker (modeled on the round-12 telemetry ticker: dispatch under
the lock, land off-lock) decays the sketch, reads every row's estimate,
and demotes the lowest-estimate unpinned rows whenever the resident
count exceeds the ``SENTINEL_HOT_ROWS`` target — so LRU pressure from
new keys lands on sketch-cold rows, never on the measured hot set.
Proactive demotion requires the Python registry's ``evict_name``; on
the native C++ table only LRU-overflow demotion runs (documented in
OPERATIONS.md).

Demotion attribution: the registry eviction queue carries row IDS (the
name is already gone by then), so the manager keeps a shadow
``row → name`` map maintained at every intern site
(:meth:`TierManager.note_interned`). Rows reallocated by paths that
bypass interning (rule-compile pins) resync from ``registry.name_of``
at drain time and their previous owner's state is dropped
unattributed — the pre-round-15 behavior, counted but not restored.
"""

from __future__ import annotations

import collections
import functools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sentinel_tpu.core.pending import start_host_copy
from sentinel_tpu.core.registry import ENTRY_NODE_ROW
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.obs.hist import LogHistogram
from sentinel_tpu.stats import events as ev
from sentinel_tpu.tiering import sketch as sk
from sentinel_tpu.tiering.coldtier import ColdEntry, ColdTier, settle_entry_np

HOT_ROWS_ENV = "SENTINEL_HOT_ROWS"
SKETCH_BITS_ENV = "SENTINEL_SKETCH_BITS"
SKETCH_ROWS_ENV = "SENTINEL_SKETCH_ROWS"
TIER_TICK_MS_ENV = "SENTINEL_TIER_TICK_MS"
TIERING_DISABLE_ENV = "SENTINEL_TIERING_DISABLE"
TIER_COLD_MAX_ENV = "SENTINEL_TIER_COLD_MAX"

DEFAULT_TICK_MS = 200
# un-landed demote payloads tolerated before the drain side force-lands
# inline (the ticker normally lands them; this bounds device-buffer
# retention when no ticker runs, e.g. short-lived test engines)
PENDING_LAND_MAX = 64

NEVER = -(2 ** 30)
_I32MAX = np.iinfo(np.int32).max


def _env_int(env: str, default: Optional[int], lo: int,
             hi: int) -> Optional[int]:
    raw = os.environ.get(env, "")
    if not raw:
        return default
    try:
        return max(lo, min(hi, int(raw)))
    except ValueError:
        return default


def tier_hot_rows(default: Optional[int] = None) -> Optional[int]:
    """Resident-row target for the ticker's proactive demotion; default
    None = the full table (LRU-overflow demotion only)."""
    return _env_int(HOT_ROWS_ENV, default, 64, 1 << 24)


def tier_sketch_bits(default: int = sk.DEFAULT_BITS) -> int:
    return _env_int(SKETCH_BITS_ENV, default, 4, 22)


def tier_sketch_rows(default: int = sk.DEFAULT_ROWS) -> int:
    return _env_int(SKETCH_ROWS_ENV, default, 1, 8)


def tier_tick_ms(default: int = DEFAULT_TICK_MS) -> int:
    return _env_int(TIER_TICK_MS_ENV, default, 10, 60000)


def tier_cold_max(default: int = 0) -> int:
    """Cold-tier entry bound; 0 = unbounded (the default)."""
    return _env_int(TIER_COLD_MAX_ENV, default, 0, 1 << 31)


def tiering_disabled() -> bool:
    return os.environ.get(TIERING_DISABLE_ENV, "").lower() in (
        "1", "true", "on", "yes")


@functools.lru_cache(maxsize=None)
def _jit_extract(spec):
    from sentinel_tpu.engine.pipeline import extract_resource_rows
    return jax.jit(functools.partial(extract_resource_rows, spec))


@functools.lru_cache(maxsize=None)
def _jit_restore(spec):
    from sentinel_tpu.engine.pipeline import restore_resource_rows
    return jax.jit(functools.partial(restore_resource_rows, spec))


def _pad_pow2(n: int) -> int:
    # pow2 padding keeps the extract/restore jit cache bounded per spec
    p = 1
    while p < n:
        p <<= 1
    return p


class TierManager:
    """Per-:class:`~sentinel_tpu.runtime.Sentinel` tiering service
    (``Sentinel.tiering``). Host structures live under a manager-local
    lock; the ``*_locked`` hooks additionally run under the ENGINE lock
    (they touch ``sentinel._state``). Lock order is always engine lock
    → manager lock, never the reverse."""

    def __init__(self, sentinel, *, enabled: Optional[bool] = None) -> None:
        self._sentinel = sentinel
        self._obs = sentinel.obs
        if enabled is None:
            enabled = not tiering_disabled()
        self.enabled = bool(enabled)
        self.hot_rows = tier_hot_rows()
        self.cold = ColdTier(tier_cold_max() or None)
        self.migration_hist = LogHistogram()
        self._lock = threading.Lock()
        # row → current owner name, maintained at every intern site
        self._shadow: Dict[int, str] = {}
        # row → FIRST victim name since the last eviction drain (later
        # victims of the same row lived entirely between drains: no
        # decide ever saw them, nothing on-device to save)
        self._pending_demote: Dict[int, str] = {}
        # name → row awaiting a cold→hot restore at the next drain
        self._pending_promote: Dict[str, int] = {}
        # names whose demote payload is dispatched but not yet landed
        self._pending_land: Dict[str, dict] = {}
        self._land_q: "collections.deque" = collections.deque()
        self._est_q: "collections.deque" = collections.deque()
        # flow-rule reload log: second-window now_idx per reload; a cold
        # entry replays the tail it slept through at promote time
        self._reload_idxs: List[int] = []
        self._sketch = None
        self._sketch_update = None
        self._ticks = 0
        self._last_est: Optional[np.ndarray] = None
        # round 16 — epilogue carry cadence: when armed (CadenceScheduler,
        # serving.py), serving traffic runs the decay+estimate inside the
        # fused dispatch and the ticker only self-dispatches on idle gaps
        self._carry_ms: Optional[int] = None
        self._last_tick_ms = int(sentinel.clock.now_ms())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        if self.enabled:
            self._sketch = sk.init_sketch(tier_sketch_rows(),
                                          tier_sketch_bits())
            self._sketch_update = sk.jit_update()
        # demote listeners (frontend/batcher.py prunes its name→row
        # cache so a demoted key re-interns — and promotes — instead of
        # dispatching against a recycled row)
        self._demote_listeners: list = []
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)

    # ---- intern-time hooks (outside the engine lock) ------------------

    def note_interned(self, names, rows, tick: bool = True) -> None:
        """Record name→row ownership for a just-interned batch and
        classify each occurrence: resident name → ``tier.hot_hit``;
        name the cold tier (or an in-flight demote) knows →
        ``tier.cold_miss`` + queued promotion; first-sight name →
        neither (a brand-new key is not a *miss* of anything — see the
        hit-rate note in OPERATIONS.md). O(distinct names) python —
        serving loops front this with the batcher's name→row cache, so
        only cache misses pay it. ``tick=False`` (rule-load pin paths,
        runtime._update_rule_pins_locked) keeps the shadow map and
        promotion queue exact without counting control-plane interns
        into the serving hit rate."""
        if not self.enabled:
            return
        hot = cold = 0
        with self._lock:
            seen: Dict[str, list] = {}    # name → [count, classification]
            # Two passes so classification cannot depend on intra-batch
            # ORDER: when name A's fresh row displaced name B and B is
            # ALSO in this batch at a new row (a rule reload re-interning
            # a full pinned set does exactly this), B's cold-miss test
            # must see the demote intent A's displacement records — in
            # one pass that held only if A happened to come first, and
            # the pin path feeds this from a Python set, so B's window
            # state was dropped or kept by hash order (the real cause of
            # the seed-1602 tiered-vs-resident divergence once blamed on
            # the staging ring).
            fresh: List[Tuple[str, int]] = []
            for i, name in enumerate(names):
                rec = seen.get(name)
                if rec is not None:
                    rec[0] += 1
                    continue
                row = int(rows[i])
                prev = self._shadow.get(row)
                if prev == name:
                    seen[name] = [1, "hot"]
                    continue
                self._shadow[row] = name
                if prev is not None:
                    self._pending_demote.setdefault(row, prev)
                seen[name] = [1, "new"]
                fresh.append((name, row))
            for name, row in fresh:
                if (name in self.cold or name in self._pending_land
                        or any(v == name
                               for v in self._pending_demote.values())):
                    self._pending_promote[name] = row
                    seen[name][1] = "cold"
            for _name, (cnt, kind) in seen.items():
                if kind == "hot":
                    hot += cnt
                elif kind == "cold":
                    cold += cnt
        if tick and self._obs.enabled:
            if hot:
                self._obs.counters.add(obs_keys.TIER_HOT_HIT, hot)
            if cold:
                self._obs.counters.add(obs_keys.TIER_COLD_MISS, cold)

    def note_hot_hits(self, n: int) -> None:
        """Frontend name→row cache hits: resident by construction (the
        cache is pruned on demotion), counted here so the hit rate
        covers the whole serving path."""
        if self.enabled and n and self._obs.enabled:
            self._obs.counters.add(obs_keys.TIER_HOT_HIT, n)

    def add_demote_listener(self, fn) -> None:
        """``fn(names: List[str])`` fires when keys leave the hot tier
        (called from the eviction drain, still under the engine lock —
        keep it O(names))."""
        self._demote_listeners.append(fn)

    # ---- engine-lock hooks -------------------------------------------

    def observe_locked(self, rows_dev, valid_dev) -> bool:
        """Sketch update from a decide batch's device row array —
        dispatch-only (conservative-update count-min; see sketch.py).
        The update op halves the table inside the jit when an estimate
        crosses the overflow cap, so counters stay bounded even on an
        engine that never starts the ticker; the flag is dropped here
        (syncing it would stall the decide) and the overflow COUNTER is
        ticked host-side from the ticker's estimate readback.

        Round 16: this standalone dispatch is the DISABLED/FALLBACK path
        — with ``SENTINEL_SINGLE_DISPATCH`` on, the runtime fuses the
        identical :func:`sketch.update_sketch` into the decide program
        (see :meth:`sketch_for_fuse_locked`) and never calls this.
        Returns whether a dispatch was actually issued (the runtime's
        ``pipeline.dispatches`` accounting)."""
        if self._sketch is None:
            return False
        self._sketch, _overflow = self._sketch_update(
            self._sketch, rows_dev, valid_dev)
        return True

    # ---- round 16: single-dispatch fusion surface ---------------------

    def sketch_for_fuse_locked(self):
        """Engine lock held: the sketch table to thread through a
        sketch-fused decide dispatch, or None when tiering (or its
        sketch) is off — None tells the runtime to fall back to the
        legacy program + :meth:`observe_locked` composition."""
        if not self.enabled or self._closed:
            return None
        return self._sketch

    def set_sketch_locked(self, sketch) -> None:
        """Engine lock held: store the donated-output sketch returned by
        a sketch-fused dispatch."""
        self._sketch = sketch

    def arm_carry(self, interval_ms: int) -> None:
        """Let serving traffic carry the decay+estimate tick inside the
        fused dispatch at this cadence (CadenceScheduler, serving.py)."""
        with self._lock:
            self._carry_ms = max(1, int(interval_ms))
            self._last_tick_ms = int(self._sentinel.clock.now_ms())

    def disarm_carry(self) -> None:
        with self._lock:
            self._carry_ms = None

    def last_tick_ms(self) -> int:
        with self._lock:
            return self._last_tick_ms

    def carry_due_locked(self, now_ms: int) -> bool:
        """Engine lock held: claim one carried tick if the cadence is
        armed and due. The claim updates ``_last_tick_ms`` immediately —
        the caller dispatches the epilogue in the same lock hold, so a
        concurrent self-dispatch fallback won't double-tick."""
        if (not self.enabled or self._closed or self._sketch is None):
            return False
        with self._lock:
            if (self._carry_ms is None
                    or now_ms - self._last_tick_ms < self._carry_ms):
                return False
            self._last_tick_ms = int(now_ms)
            return True

    def queue_estimates(self, est) -> None:
        """Queue an epilogue-carried estimate readback (engine lock
        held; the host copy was started by the runtime). Counted as a
        tick — :meth:`drain` lands it exactly like a self-dispatched
        one."""
        with self._lock:
            self._est_q.append(est)
            self._ticks += 1

    def pre_invalidate_locked(self, evicted: List[int], now_ms: int) -> None:
        """Demote snapshot: gather the evicted rows' state BEFORE the
        invalidate destroys it. Dispatch + queue only; ``np.asarray``
        happens on the tiering thread (or force-lands at promote).
        Stream ordering guarantees the gather reads pre-invalidate
        values even though the invalidate is dispatched right after."""
        if not self.enabled:
            return
        sn = self._sentinel
        victims: List[Tuple[str, int]] = []
        with self._lock:
            for row in evicted:
                name = self._pending_demote.pop(row, None)
                from_queue = name is not None
                if name is None:
                    name = self._shadow.get(row)
                cur = sn.resources.name_of(row)
                if cur is not None:
                    self._shadow[row] = cur
                else:
                    self._shadow.pop(row, None)
                if name is None or row == ENTRY_NODE_ROW:
                    continue    # unattributable (pin-path reallocation)
                if not from_queue and name == cur:
                    continue    # stale duplicate queue entry; still owned
                victims.append((name, row))
        if not victims:
            return
        # alt slots: hashed (resource × origin/context) slices travel
        # with their HOST identity so the promote can re-hash them
        alt_ids: List[Tuple[int, int, int]] = []   # (victim_i, kind, key)
        alt_slots: List[int] = []
        for vi, (_name, row) in enumerate(victims):
            slots = sn._alt_rows_by_row.get(row, {})
            items = (slots.items() if isinstance(slots, dict)
                     else ((s, None) for s in slots))
            for slot, ident in items:
                if ident is None:
                    continue    # identity unknown: slice not portable
                alt_ids.append((vi, ident[0], ident[1]))
                alt_slots.append(slot)
        k = len(victims)
        kp = _pad_pow2(k)
        ka = _pad_pow2(len(alt_slots)) if alt_slots else 1
        rows_arr = np.full(kp, sn.spec.rows, np.int32)    # pad → dropped
        rows_arr[:k] = [r for _n, r in victims]
        alt_arr = np.full(ka, sn.spec.alt_rows, np.int32)
        if alt_slots:
            alt_arr[:len(alt_slots)] = alt_slots
        payload = _jit_extract(sn.spec)(
            sn._state, jnp.asarray(rows_arr), jnp.asarray(alt_arr))
        start_host_copy(tuple(jax.tree_util.tree_leaves(payload)))
        with self._lock:
            rec = {"victims": victims, "alt_ids": alt_ids,
                   "payload": payload, "now_ms": now_ms,
                   "gen": len(self._reload_idxs), "landed": False,
                   "lock": threading.Lock()}
            for name, _row in victims:
                self._pending_land[name] = rec
            self._land_q.append(rec)
            force = len(self._land_q) > PENDING_LAND_MAX
        if self._obs.enabled:
            self._obs.counters.add(obs_keys.TIER_DEMOTED, k)
        if force:
            self._land_all()
        if self._demote_listeners:
            names = [n for n, _r in victims]
            for fn in self._demote_listeners:
                try:
                    fn(names)
                except Exception:
                    pass

    def post_invalidate_locked(self, now_ms: int) -> None:
        """Promote every pending cold key into its freshly allocated
        (and just-invalidated) row — the synchronous half of the slow
        path. Runs under the engine lock so the decide that interned
        the key sees the restored row."""
        if not self.enabled:
            return
        with self._lock:
            if not self._pending_promote:
                return
            todo = list(self._pending_promote.items())
            self._pending_promote.clear()
        sn = self._sentinel
        t0 = time.monotonic_ns()
        entries: List[Tuple[str, int, ColdEntry]] = []
        for name, row in todo:
            with self._lock:
                if self._shadow.get(row) != name:
                    # row recycled again before this drain; the entry
                    # stays cold for the next intern of the name
                    continue
                pend = self._pending_land.get(name)
            if pend is not None:
                # force-land THIS rec directly — a queue-level
                # _land_all would no-op if the tiering thread already
                # dequeued it but hasn't finished landing, and the
                # promote below would then pop a missing entry and
                # silently serve a zeroed row; _land_one's per-rec
                # lock instead blocks until the in-flight land is done
                self._land_one(pend)
            entry = self.cold.pop(name)
            if entry is None:
                continue            # dropped (bounded cold tier)
            if entry.sec_counters.shape[0] != sn.spec.second.buckets:
                # extracted under a previous window geometry and missed
                # by the geometry-change conversion (a straggler that
                # landed after it): restoring would scatter mismatched
                # shapes, and the cold-reset semantic says its second
                # windows are void anyway — drop; the key re-enters
                # fresh, exactly like a resident row post-change
                continue
            # replay the flow reloads this key slept through, each with
            # THAT reload's now_idx — bit-parity with the resident settle
            with self._lock:
                idxs = self._reload_idxs[entry.reload_gen:]
            for idx in idxs:
                settle_entry_np(sn.spec.second.buckets, entry, idx, ev.PASS)
            entries.append((name, row, entry))
        if not entries:
            return
        self._restore_locked(entries)
        if self._obs.enabled:
            self._obs.counters.add(obs_keys.TIER_PROMOTED, len(entries))
        self.migration_hist.record(time.monotonic_ns() - t0)

    def _restore_locked(self, entries) -> None:
        """One jitted scatter for the whole promote batch."""
        from sentinel_tpu.engine.pipeline import ResourceRowSlice
        from sentinel_tpu.runtime import _alt_hash
        from sentinel_tpu.stats.window import WindowState
        sn = self._sentinel
        spec = sn.spec
        k = len(entries)
        kp = _pad_pow2(k)
        B = spec.second.buckets
        e0 = entries[0][2]
        ne = e0.sec_counters.shape[-1]
        brt = e0.sec_rt_sum.shape[0]
        mb, mbrt = e0.min_stamps.shape[0], e0.min_rt_sum.shape[0]
        sec_c = np.zeros((kp, B, ne), np.int32)
        sec_s = np.full((kp, B), NEVER, np.int32)
        sec_rt = np.zeros((kp, brt), np.float32)
        sec_mr = np.full((kp, brt), _I32MAX, np.int32)
        min_c = np.zeros((kp, max(mb, 1), ne), np.int32)
        min_s = np.full((kp, max(mb, 1)), NEVER, np.int32)
        min_rt = np.zeros((kp, mbrt), np.float32)
        min_mr = np.full((kp, mbrt), _I32MAX, np.int32)
        thr = np.zeros(kp, np.int32)
        occ_c = np.zeros((kp, B + 1), np.float32)
        occ_w = np.full((kp, B + 1), NEVER, np.int32)
        hb = spec.hist_buckets
        # zeros for entries that predate the histogram table (a cold
        # entry demoted before the feature was enabled restores with an
        # empty — not stale — tail view)
        rt_h = np.zeros((kp, hb), np.int32) if hb else None
        rows_arr = np.full(kp, spec.rows, np.int32)
        alt_rows: List[int] = []
        alt_payload: List[tuple] = []
        for i, (_name, row, e) in enumerate(entries):
            rows_arr[i] = row
            sec_c[i], sec_s[i] = e.sec_counters, e.sec_stamps
            sec_rt[i], sec_mr[i] = e.sec_rt_sum, e.sec_min_rt
            if mb:
                min_c[i], min_s[i] = e.min_counters, e.min_stamps
                min_rt[i], min_mr[i] = e.min_rt_sum, e.min_min_rt
            thr[i] = e.threads
            occ_c[i], occ_w[i] = e.occ_cnt, e.occ_win
            if rt_h is not None and e.rt_hist is not None \
                    and e.rt_hist.shape[0] == hb:
                rt_h[i] = e.rt_hist
            for (kind, key_id), alt in e.alts.items():
                slot = _alt_hash(row, kind, key_id, spec.alt_rows)
                slots = sn._alt_rows_by_row.setdefault(row, {})
                if isinstance(slots, dict):
                    slots[slot] = (kind, key_id)
                else:
                    slots.add(slot)
                alt_rows.append(slot)
                alt_payload.append(alt)
        ka = _pad_pow2(len(alt_rows)) if alt_rows else 1
        alt_arr = np.full(ka, spec.alt_rows, np.int32)
        alt_c = np.zeros((ka, B, ne), np.int32)
        alt_s = np.full((ka, B), NEVER, np.int32)
        alt_rt = np.zeros((ka, brt), np.float32)
        alt_mr = np.full((ka, brt), _I32MAX, np.int32)
        alt_thr = np.zeros(ka, np.int32)
        for j, alt in enumerate(alt_payload):
            alt_arr[j] = alt_rows[j]
            alt_c[j], alt_s[j], alt_rt[j], alt_mr[j], alt_thr[j] = alt
        payload = ResourceRowSlice(
            second=WindowState(jnp.asarray(sec_c), jnp.asarray(sec_s),
                               jnp.asarray(sec_rt), jnp.asarray(sec_mr)),
            minute=WindowState(jnp.asarray(min_c), jnp.asarray(min_s),
                               jnp.asarray(min_rt), jnp.asarray(min_mr)),
            threads=jnp.asarray(thr),
            occ_cnt=jnp.asarray(occ_c), occ_win=jnp.asarray(occ_w),
            alt_second=WindowState(jnp.asarray(alt_c), jnp.asarray(alt_s),
                                   jnp.asarray(alt_rt), jnp.asarray(alt_mr)),
            alt_threads=jnp.asarray(alt_thr),
            rt_hist=jnp.asarray(rt_h) if rt_h is not None else None)
        sn._state = _jit_restore(spec)(
            sn._state, jnp.asarray(rows_arr), payload, jnp.asarray(alt_arr))

    def on_rules_reloaded_locked(self, now_idx: int) -> None:
        """Flow-rule reload: resident rows just had their landed
        bookings settled at ``now_idx``; log it so cold entries replay
        the same settle at promote time."""
        if not self.enabled:
            return
        with self._lock:
            self._reload_idxs.append(int(now_idx))

    def on_geometry_changed_locked(self) -> None:
        """Live second-window geometry change
        (``runtime.update_window_geometry``, engine lock held): every
        cold entry and in-flight demote payload was extracted under the
        OLD bucket count — promoting one later would scatter mismatched
        shapes into the new state (numpy shape error / IndexError on
        the serving path). Land every in-flight payload first (host
        numpy, still old-geometry — the per-rec lock in ``_land_one``
        covers recs the tiering thread holds mid-land), then cold-reset
        each entry's second windows + booking ring to the new bucket
        count, minute ring and thread gauge carrying over — exactly
        what resident rows get, so demote→change→promote stays
        bit-identical to staying resident. The reload-replay log
        restarts: pre-change reloads settled into buckets that no
        longer exist and every entry is reset-empty."""
        if not self.enabled:
            return
        with self._lock:
            recs = list({id(r): r for r in
                         self._pending_land.values()}.values())
        for rec in recs:
            self._land_one(rec)
        with self._lock:
            self._land_q.clear()    # all landed (or marked) above
            self._reload_idxs.clear()
        self.cold.convert_geometry(self._sentinel.spec.second.buckets)

    # ---- landing (tiering thread / forced) ----------------------------

    def _land_all(self) -> int:
        with self._lock:
            batch = list(self._land_q)
            self._land_q.clear()
        for rec in batch:
            self._land_one(rec)
        return len(batch)

    def _land_one(self, rec) -> None:
        # per-rec lock: the engine side (post_invalidate_locked,
        # on_geometry_changed_locked) may force-land a rec the tiering
        # thread has already dequeued from _land_q — whoever arrives
        # second blocks until the first fully lands (cold.put done),
        # then no-ops, so a force-land always leaves the entry visible
        # to the cold.pop that follows it
        with rec["lock"]:
            self._land_one_held(rec)

    def _land_one_held(self, rec) -> None:
        if rec["landed"]:
            return
        p = rec["payload"]
        sec = tuple(np.asarray(x) for x in p.second)
        mnt = tuple(np.asarray(x) for x in p.minute)
        threads = np.asarray(p.threads)
        occ_c, occ_w = np.asarray(p.occ_cnt), np.asarray(p.occ_win)
        alt_sec = tuple(np.asarray(x) for x in p.alt_second)
        alt_thr = np.asarray(p.alt_threads)
        rh = np.asarray(p.rt_hist) if p.rt_hist is not None else None
        for vi, (name, _row) in enumerate(rec["victims"]):
            alts = {}
            for j, (avi, kind, key_id) in enumerate(rec["alt_ids"]):
                if avi == vi:
                    alts[(kind, key_id)] = (
                        alt_sec[0][j].copy(), alt_sec[1][j].copy(),
                        alt_sec[2][j].copy(), alt_sec[3][j].copy(),
                        int(alt_thr[j]))
            entry = ColdEntry(
                sec_counters=sec[0][vi].copy(), sec_stamps=sec[1][vi].copy(),
                sec_rt_sum=sec[2][vi].copy(), sec_min_rt=sec[3][vi].copy(),
                min_counters=mnt[0][vi].copy(), min_stamps=mnt[1][vi].copy(),
                min_rt_sum=mnt[2][vi].copy(), min_min_rt=mnt[3][vi].copy(),
                threads=int(threads[vi]),
                occ_cnt=occ_c[vi].copy(), occ_win=occ_w[vi].copy(),
                alts=alts, reload_gen=rec["gen"], demoted_ms=rec["now_ms"],
                rt_hist=rh[vi].copy() if rh is not None else None)
            self.cold.put(name, entry)
            with self._lock:
                if self._pending_land.get(name) is rec:
                    del self._pending_land[name]
        rec["landed"] = True

    # ---- ticker -------------------------------------------------------

    def tick(self) -> bool:
        """Dispatch one sketch decay + full-table estimate read under
        the engine lock (no sync); queue the readback."""
        if not self.enabled or self._closed or self._sketch is None:  # graftlint: disable=LOCK002 -- lock-free early-out; a stale read only skips one tick and the next tick re-reads
            return False
        sn = self._sentinel
        with sn._lock:
            self._sketch, est = sk.jit_tick_read(sn.spec.rows)(self._sketch)
        start_host_copy((est,))
        if self._obs.enabled:
            self._obs.counters.add(obs_keys.PIPE_DISPATCH)
        with self._lock:
            self._est_q.append(est)
            self._ticks += 1
            self._last_tick_ms = int(sn.clock.now_ms())
        return True

    def drain(self) -> int:
        """Land queued demote payloads + sketch estimates OFF the
        engine lock; handle sketch overflow; run proactive demotion
        against the hot-rows target."""
        n = self._land_all()
        with self._lock:
            ests = list(self._est_q)
            self._est_q.clear()
        if ests:
            est = np.asarray(ests[-1])
            self._last_est = est
            # update_sketch already halved inline at the cap (decide
            # paths never sync); an estimate still >= cap/2 means an
            # overflow happened since the last tick — tick the counter
            # and halve again to keep headroom
            if est.size and int(est.max()) >= sk.OVERFLOW_CAP // 2:
                with self._sentinel._lock:
                    self._sketch = sk._jit_halve(self._sketch)
                if self._obs.enabled:
                    self._obs.counters.add(obs_keys.TIER_SKETCH_OVERFLOW)
            self._demote_cold_rows(est)
        return n + len(ests)

    def _demote_cold_rows(self, est: np.ndarray) -> None:
        """Evict the lowest-estimate unpinned residents down to the
        ``SENTINEL_HOT_ROWS`` target, round-robin across mesh shards
        (parallel/local_shard.py row ownership) so no shard's hot set
        thins faster than its peers'. Python registry only (the native
        table has no targeted evict; LRU-overflow demotion still
        applies there)."""
        target = self.hot_rows
        reg = self._sentinel.resources
        evict = getattr(reg, "evict_name", None)
        if target is None or evict is None:
            return
        items = reg.items()
        over = len(items) - int(target)
        if over <= 0:
            return
        from sentinel_tpu.parallel.local_shard import shard_of_rows
        cand = [(int(est[row]), name, row) for name, row in items
                if row != ENTRY_NODE_ROW and row < len(est)]
        cand.sort()
        shards = shard_of_rows(self._sentinel.spec.rows,
                               self._sentinel.mesh,
                               np.asarray([c[2] for c in cand], np.int32))
        by_shard: Dict[int, collections.deque] = {}
        for c, s in zip(cand, shards):
            by_shard.setdefault(int(s), collections.deque()).append(c)
        done = 0
        while done < over and by_shard:
            for s in list(by_shard):
                q = by_shard[s]
                while q:
                    _e, name, row = q.popleft()
                    # record intent BEFORE evict_name frees the row: a
                    # re-intern of this name in the window after the
                    # registry pops the row but before intent lands
                    # would otherwise classify hot against the stale
                    # shadow entry, and the next drain would invalidate
                    # the row without queuing its promotion — silently
                    # zeroing a resident key
                    with self._lock:
                        if self._shadow.get(row) != name:
                            continue    # re-owned since the estimate
                        claimed = row not in self._pending_demote
                        if claimed:
                            self._pending_demote[row] = name
                        del self._shadow[row]
                    if evict(name):
                        done += 1
                        break
                    # evict refused (pinned / raced away): roll back so
                    # the name doesn't look cold while still resident
                    with self._lock:
                        if (claimed and
                                self._pending_demote.get(row) == name):
                            del self._pending_demote[row]
                        self._shadow.setdefault(row, name)
                if not q:
                    del by_shard[s]
                if done >= over:
                    break

    def poll(self) -> int:
        self.tick()
        return self.drain()

    def start(self, interval_sec: Optional[float] = None) -> None:
        """Start the tiering daemon (no-op when disabled/running)."""
        if not self.enabled or self._thread is not None or self._closed:
            return
        if interval_sec is None:
            interval_sec = tier_tick_ms() / 1000.0
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_sec):
                try:
                    self.poll()
                except Exception:   # pragma: no cover — keep daemon alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sentinel-tiering")
        self._thread.start()

    def stop(self) -> None:
        """Idempotent; registered with ``Sentinel.register_shutdown``."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._closed:
            return
        self._closed = True
        try:
            self._land_all()
        except Exception:   # teardown must not depend on device health
            pass

    # ---- read surface -------------------------------------------------

    def snapshot(self) -> Dict:
        """The serving-bench artifact / transport-command body."""
        c = self._obs.counters
        with self._lock:
            pend = len(self._land_q)
        p50 = self.migration_hist.percentile(0.50)
        p99 = self.migration_hist.percentile(0.99)
        return {
            "enabled": self.enabled,
            "hot_rows_target": self.hot_rows,
            "resident": len(self._sentinel.resources),
            "cold": len(self.cold),
            "cold_dropped": self.cold.dropped,
            "pending_land": pend,
            "ticks": self._ticks,  # graftlint: disable=LOCK002 -- diagnostic snapshot; a torn counter read is harmless
            "hot_hit": c.get(obs_keys.TIER_HOT_HIT),
            "cold_miss": c.get(obs_keys.TIER_COLD_MISS),
            "promoted": c.get(obs_keys.TIER_PROMOTED),
            "demoted": c.get(obs_keys.TIER_DEMOTED),
            "sketch_overflow": c.get(obs_keys.TIER_SKETCH_OVERFLOW),
            "migrate_p50_ms": None if p50 is None else p50 / 1e6,
            "migrate_p99_ms": None if p99 is None else p99 / 1e6,
        }

    def hit_rate(self) -> Optional[float]:
        """hot_hit / (hot_hit + cold_miss) — None before any classified
        intern. First-sight registrations count as neither (a brand-new
        key never had state to miss; ``tier.cold_miss`` measures
        hot-tier sizing, not keyspace size — see OPERATIONS.md)."""
        c = self._obs.counters
        h = c.get(obs_keys.TIER_HOT_HIT)
        m = c.get(obs_keys.TIER_COLD_MISS)
        return h / (h + m) if (h + m) else None
