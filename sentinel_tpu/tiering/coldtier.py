"""Host-memory cold tier: evicted rows' complete state, by name.

One :class:`ColdEntry` per demoted resource holds everything
``engine.pipeline.invalidate_resource_rows`` would have destroyed —
second/minute window slices, the thread gauge, the occupy booking ring,
and the hashed alt (resource × origin/context) slices keyed by their
HOST identity ``(kind, key_id)`` so promotion can re-hash them onto the
new row's slots. Window stamps and booking target windows are absolute
indices, so an entry is time-portable: restored at any later instant it
reads exactly as the live row would have.

The one transform an entry may need before restore is the rule-reload
replay: ``Sentinel.load_flow_rules`` settles every RESIDENT row's
landed occupy bookings into its second window (``settle_occupied``)
and carries pending ones into the fresh ring. A row that was cold at
reload time missed that settle, so :func:`settle_entry_np` replays it
host-side — a numpy port of ``stats.window.settle_occupied`` (integer
and float32 adds only, bit-identical by construction; pinned by
tests/test_tiering.py) — once per reload the entry slept through, each
with THAT reload's ``now_idx``. After the replay the restored row is
bit-identical to one that stayed resident.

Capacity: unbounded by default (the whole point — key cardinality is no
longer table-bound); ``SENTINEL_TIER_COLD_MAX`` bounds host memory by
dropping the oldest entries (a dropped key re-enters as a fresh
resource, the pre-round-15 behavior).
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

NEVER = -(2 ** 30)
_I32MAX = np.iinfo(np.int32).max


@dataclass
class ColdEntry:
    """One demoted resource's host-side state (numpy, device-free)."""

    # second window slice: counters [B, E], stamps [B], rt_sum/min_rt [B_rt]
    sec_counters: np.ndarray
    sec_stamps: np.ndarray
    sec_rt_sum: np.ndarray
    sec_min_rt: np.ndarray
    # minute window slice (empty arrays when the minute ring is disabled)
    min_counters: np.ndarray
    min_stamps: np.ndarray
    min_rt_sum: np.ndarray
    min_min_rt: np.ndarray
    threads: int
    occ_cnt: np.ndarray            # float32 [B+1]
    occ_win: np.ndarray            # int32 [B+1]
    # (kind, key_id) → (counters [B,E], stamps [B], rt_sum, min_rt, threads)
    alts: Dict[Tuple[int, int], tuple] = field(default_factory=dict)
    reload_gen: int = 0            # flow reloads seen BEFORE demotion
    demoted_ms: int = 0
    # round 20: cumulative per-resource RT histogram row (int32 [HB]);
    # None when the engine has no histogram table or the entry predates
    # the feature. Time-portable by construction (no stamps): it rides
    # demote→promote untouched, and reset_entry_geometry_np deliberately
    # carries it over — the table is cumulative-forever, not windowed.
    rt_hist: Optional[np.ndarray] = None


def settle_entry_np(buckets: int, entry: ColdEntry, now_idx: int,
                    event: int) -> None:
    """In-place replay of one missed flow-rule reload on a cold entry —
    the numpy mirror of ``stats.window.settle_occupied`` for a single
    row. LANDED bookings (``0 <= now_idx - w < buckets``) credit
    ``event`` counts into their target bucket (dead buckets reset and
    restamp first), PENDING ones (``now_idx - w == -1``) survive in the
    ring, anything older expires — exactly what the resident rows got
    from ``_jit_settle_occupied`` at that reload."""
    B = buckets
    track_rt = entry.sec_rt_sum.shape[0] > 0
    pend_cnt = np.zeros_like(entry.occ_cnt)
    pend_win = np.full_like(entry.occ_win, NEVER)
    for s in range(entry.occ_cnt.shape[0]):
        w = int(entry.occ_win[s])
        c = entry.occ_cnt[s]
        age = np.int32(now_idx) - np.int32(w)   # wraparound-safe diff
        if age >= 0 and age < B and c > 0:      # landed
            k = w % B
            if entry.sec_stamps[k] != np.int32(w):   # dead bucket: reset
                entry.sec_counters[k, :] = 0
                if track_rt:
                    entry.sec_rt_sum[k] = 0.0
                    entry.sec_min_rt[k] = _I32MAX
                entry.sec_stamps[k] = np.int32(w)
            entry.sec_counters[k, event] += np.int32(c)
        elif age == -1 and c > 0:               # pending: carry
            pend_cnt[s] = c
            pend_win[s] = w
    entry.occ_cnt = pend_cnt
    entry.occ_win = pend_win


def reset_entry_geometry_np(entry: ColdEntry, buckets: int) -> None:
    """In-place second-window cold-reset of one entry to a NEW bucket
    count — the cold-tier mirror of ``runtime.update_window_geometry``,
    which swaps fresh second windows, booking rings, and flow shaping
    state into every RESIDENT row while the minute ring and thread
    gauges carry over. A cold entry gets exactly the same treatment so
    a later promote (a) scatters shapes that match the new spec and
    (b) restores the row bit-identical to one that stayed resident
    through the change. ``reload_gen`` rewinds to 0: the manager clears
    its reload-replay log at a geometry change (pre-change reloads
    settled into buckets that no longer exist, and the reset entry has
    nothing left to settle)."""
    B = int(buckets)
    ne = entry.sec_counters.shape[-1]
    brt = B if entry.sec_rt_sum.shape[0] else 0
    entry.sec_counters = np.zeros((B, ne), np.int32)
    entry.sec_stamps = np.full(B, NEVER, np.int32)
    entry.sec_rt_sum = np.zeros(brt, np.float32)
    entry.sec_min_rt = np.full(brt, _I32MAX, np.int32)
    entry.occ_cnt = np.zeros(B + 1, np.float32)
    entry.occ_win = np.full(B + 1, NEVER, np.int32)
    entry.alts = {
        ident: (np.zeros((B, ne), np.int32), np.full(B, NEVER, np.int32),
                np.zeros(brt, np.float32), np.full(brt, _I32MAX, np.int32),
                alt[4])
        for ident, alt in entry.alts.items()}
    entry.reload_gen = 0


class ColdTier:
    """Locked name → :class:`ColdEntry` store with optional LRU bound."""

    def __init__(self, max_entries: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, ColdEntry]" = \
            collections.OrderedDict()
        self._max = max_entries if max_entries and max_entries > 0 else None
        self._dropped = 0

    def put(self, name: str, entry: ColdEntry) -> None:
        with self._lock:
            self._entries[name] = entry
            self._entries.move_to_end(name)
            if self._max is not None:
                while len(self._entries) > self._max:
                    self._entries.popitem(last=False)
                    self._dropped += 1

    def pop(self, name: str) -> Optional[ColdEntry]:
        with self._lock:
            return self._entries.pop(name, None)

    def convert_geometry(self, buckets: int) -> None:
        """Cold-reset every entry's second windows + booking ring to a
        new bucket count (live geometry change); see
        :func:`reset_entry_geometry_np`."""
        with self._lock:
            for entry in self._entries.values():
                reset_entry_geometry_np(entry, buckets)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def names(self, limit: int = 32) -> List[str]:
        with self._lock:
            out = []
            for n in reversed(self._entries):
                out.append(n)
                if len(out) >= limit:
                    break
            return out
