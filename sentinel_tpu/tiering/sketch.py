"""Conservative-update count-min sketch over resource ROW ids (device).

Hot-set discovery for the tiered state machine (Cormode & Muthukrishnan
2005, with the conservative-update variant: a counter only rises to the
new minimum estimate, which tightens over-estimation for skewed
streams). The sketch is tiny — ``SR`` hash rows × ``W = 2**bits``
buckets of int32 — and is updated from each decide batch's row array
UNDER the engine lock as a dispatch-only jitted op (no host sync, the
telemetry-tick discipline); the tiering ticker reads estimates
asynchronously.

Access shape honesty (the ops/pallas_kernels.py methodology): the
update is a scatter-max of ``N`` batch elements into an ``[SR, W]``
table. Three implementations of the identical math live behind
:data:`SKETCH_IMPLS` — ``scatter`` (native ``.at[].max``), ``onehot``
(masked one-hot reduce-max, the MXU-shaped candidate) and ``segment``
(``jax.ops.segment_max``) — and ``benchmarks/sketch_ab.py`` times them
on the real device before a kernel is committed. On every shape
measured so far the XLA scatter path wins (BASELINE.md round 15), so
``DEFAULT_IMPL = "scatter"`` and no Pallas kernel ships; the seam stays
so a future chip profile can flip one string.

Hash family: multiply-shift over odd 32-bit constants
(``h_s(x) = ((x * C_s) >> 15) & (W - 1)``) — int32 overflow wraps,
which is exactly the mod-2^32 arithmetic the scheme wants.

Decay: the ticker applies ``c -= max(c >> DECAY_SHIFT, 1)`` (floored
at zero) per tick so the sketch tracks the RECENT hot set, not
all-time counts — the ``min 1`` term matters: a pure shift-decay can
never move a counter below ``2**DECAY_SHIFT - 1``, leaving permanent
floor estimates on cold rows. Overflow: :func:`update_sketch` halves
the whole table INSIDE the jitted op whenever any estimate crosses
:data:`OVERFLOW_CAP` (frequencies are relative, halving preserves
ranking), so counters are bounded — and can never wrap int32 — even on
an engine that never starts the ticker; the returned overflow flag and
the ticker's estimate readback only drive the ``tier.sketch_overflow``
accounting.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BITS = 12        # W = 4096 buckets per hash row
DEFAULT_ROWS = 4         # SR hash rows
DECAY_SHIFT = 3          # per-tick decay: c -= c >> 3 (~12%/tick)
OVERFLOW_CAP = 1 << 30   # halve the table past this estimate

# odd multiply-shift constants (Knuth/Dietzfelbinger family); 8 rows max
_HASH_CONSTS = np.array(
    [0x9E3779B1, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F,
     0x165667B1, 0xD3A2646D, 0xFD7046C5, 0xB55A4F09], np.uint32)


def init_sketch(sketch_rows: int = DEFAULT_ROWS,
                bits: int = DEFAULT_BITS) -> jnp.ndarray:
    """Fresh zero table int32[SR, W]."""
    sketch_rows = max(1, min(int(sketch_rows), len(_HASH_CONSTS)))
    return jnp.zeros((sketch_rows, 1 << int(bits)), jnp.int32)


def _bucket_idx(counts: jnp.ndarray, items: jnp.ndarray) -> jnp.ndarray:
    """[SR, N] bucket index per (hash row, item) — multiply-shift."""
    sr, w = counts.shape
    consts = jnp.asarray(_HASH_CONSTS[:sr].astype(np.int32))
    prod = items[None, :].astype(jnp.int32) * consts[:, None]  # wraps mod 2^32
    return jax.lax.shift_right_logical(prod, 15) & jnp.int32(w - 1)


def _estimates(counts: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Count-min read: min over hash rows of the addressed buckets."""
    sr = counts.shape[0]
    gathered = counts[jnp.arange(sr)[:, None], idx]            # [SR, N]
    return jnp.min(gathered, axis=0)                           # [N]


def _update_scatter(counts, idx, target):
    """Native scatter-max (XLA scatter; the measured winner)."""
    sr = counts.shape[0]
    rr = jnp.broadcast_to(jnp.arange(sr)[:, None], idx.shape)
    return counts.at[rr, idx].max(jnp.broadcast_to(target[None, :],
                                                   idx.shape))


def _update_onehot(counts, idx, target):
    """Masked one-hot reduce-max — the MXU-shaped candidate: builds the
    [N, W] one-hot per hash row and reduces. Memory-bound at real batch
    sizes; kept as the A/B foil."""
    sr, w = counts.shape
    out = []
    for s in range(sr):
        oh = jax.nn.one_hot(idx[s], w, dtype=jnp.int32)        # [N, W]
        cand = jnp.max(oh * target[:, None], axis=0)           # [W]
        out.append(jnp.maximum(counts[s], cand))
    return jnp.stack(out)


def _update_segment(counts, idx, target):
    """segment_max over flattened (hash row, bucket) segments."""
    sr, w = counts.shape
    flat_idx = (jnp.arange(sr)[:, None] * w + idx).reshape(-1)
    flat_val = jnp.broadcast_to(target[None, :], idx.shape).reshape(-1)
    cand = jax.ops.segment_max(flat_val, flat_idx, num_segments=sr * w)
    return jnp.maximum(counts, cand.reshape(sr, w))

# A/B seam (ops/pallas_kernels.py precedent): identical math, one string
# picks the shipped path; benchmarks/sketch_ab.py is the evidence.
SKETCH_IMPLS = {
    "scatter": _update_scatter,
    "onehot": _update_onehot,
    "segment": _update_segment,
}
DEFAULT_IMPL = "scatter"


def update_sketch(counts: jnp.ndarray, items: jnp.ndarray,
                  valid: jnp.ndarray,
                  impl: str = DEFAULT_IMPL
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Conservative-update: each valid item raises its buckets to
    ``min-estimate + 1`` (never higher). Duplicate items within one
    batch under-count by design — the error is in the conservative
    direction (a hot row's estimate can only lag, never spuriously
    spike another row hot). Invalid (padding) lanes write 0 — a no-op
    under max. Returns ``(counts', overflow)`` with ``overflow`` a bool
    scalar: any estimate crossed :data:`OVERFLOW_CAP`. The halving
    happens HERE, inside the jitted op, so the table is self-clamping
    on engines with no running ticker (dispatch-only callers may drop
    the flag; it only feeds ``tier.sketch_overflow`` accounting)."""
    idx = _bucket_idx(counts, items)                           # [SR, N]
    est = _estimates(counts, idx)                              # [N]
    target = jnp.where(valid, est + 1, 0)
    counts = SKETCH_IMPLS[impl](counts, idx, target)
    overflow = jnp.any(target >= OVERFLOW_CAP)
    counts = jnp.where(overflow, halve_sketch(counts), counts)
    return counts, overflow


def decay_sketch(counts: jnp.ndarray) -> jnp.ndarray:
    """Per-tick exponential decay (recency weighting). Nonzero counters
    lose at least 1 per tick — ``c >> DECAY_SHIFT`` alone is 0 for
    ``c < 2**DECAY_SHIFT``, which would pin cold rows at a permanent
    nonzero floor estimate forever."""
    dec = jnp.maximum(jax.lax.shift_right_logical(counts, DECAY_SHIFT),
                      jnp.minimum(counts, 1))
    return counts - dec


def halve_sketch(counts: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.shift_right_logical(counts, 1)


def estimate_all(counts: jnp.ndarray, n_rows: int) -> jnp.ndarray:
    """Estimates for every main-table row id [0, n_rows) → int32[R] —
    the ticker's demotion-ranking read (dispatched under the engine
    lock, landed off-lock)."""
    items = jnp.arange(n_rows, dtype=jnp.int32)
    return _estimates(counts, _bucket_idx(counts, items))


@functools.lru_cache(maxsize=None)
def jit_update(impl: str = DEFAULT_IMPL):
    return jax.jit(functools.partial(update_sketch, impl=impl))


def tick_read(counts: jnp.ndarray, n_rows: int
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ticker read as pure math: decay, then estimate every row.

    Shared verbatim by the self-dispatched ticker (:func:`jit_tick_read`)
    and the round-16 single-dispatch epilogue (the ``lax.cond`` branch
    runtime._build_sd_steps traces into the fused serving program) — one
    definition is what makes the carried estimates bit-identical to the
    standalone tick's."""
    counts = decay_sketch(counts)
    return counts, estimate_all(counts, n_rows)


@functools.lru_cache(maxsize=None)
def jit_tick_read(n_rows: int):
    """Fused ticker read: decay then estimate every row (fresh buffers)."""
    return jax.jit(functools.partial(tick_read, n_rows=n_rows))


_jit_halve = jax.jit(halve_sketch)
