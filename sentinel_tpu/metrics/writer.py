"""Rolling per-second metric files with a binary seek index.

Reference: ``sentinel-core/.../node/metric/MetricWriter.java`` — files named
``{app}-metrics.log.{yyyy-MM-dd}[.N]`` in the csp log dir, each with a
``.idx`` companion of (second:int64, byte-offset:int64) big-endian pairs
written at every new second (``writeIndex:186-190``); rotation on single-file
size (default 50 MB), day roll, and total-file-count pruning of oldest
(``removeMoreFiles``). Same on-disk formats here so the reference's
``MetricSearcher``/dashboard can read our files directly.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import time as _time
from typing import List, Optional, Sequence

from sentinel_tpu.metrics.node import MetricNode

METRIC_FILE = "metrics.log"
IDX_SUFFIX = ".idx"
_IDX_ENTRY = struct.Struct(">qq")   # Java DataOutputStream.writeLong × 2


def form_metric_file_name(app_name: str, pid: Optional[int] = None) -> str:
    """``MetricWriter.formMetricFileName:376-390`` (dots in app → _)."""
    name = (app_name or "").replace(".", "_")
    base = f"{name}-{METRIC_FILE}"
    if pid is not None:
        base += f".pid{pid}"
    return base


def _date_str(ms: int) -> str:
    return _time.strftime("%Y-%m-%d", _time.localtime(ms / 1000))


def _file_sort_key(name: str):
    """Order ``base.date`` < ``base.date.1`` < ``base.date.2`` …"""
    m = re.search(r"\.(\d{4}-\d{2}-\d{2})(?:\.(\d+))?$", name)
    if not m:
        return ("", 0)
    return (m.group(1), int(m.group(2) or 0))


def list_metric_files(base_dir: str, base_name: str) -> List[str]:
    """All data files (no .idx/.lck) for the app, oldest first."""
    try:
        entries = os.listdir(base_dir)
    except FileNotFoundError:
        return []
    out = [f for f in entries
           if f.startswith(base_name + ".") and not f.endswith(IDX_SUFFIX)
           and not f.endswith(".lck")]
    out.sort(key=_file_sort_key)
    return [os.path.join(base_dir, f) for f in out]


class MetricWriter:
    def __init__(self, base_dir: str, app_name: str,
                 single_file_size: int = 50 * 1024 * 1024,
                 total_file_count: int = 6,
                 use_pid: bool = False):
        self.base_dir = base_dir
        self.base_name = form_metric_file_name(
            app_name, os.getpid() if use_pid else None)
        self.single_file_size = single_file_size
        self.total_file_count = max(total_file_count, 1)
        self._lock = threading.Lock()
        self._file = None
        self._idx = None
        self._cur_path: Optional[str] = None
        self._last_second: Optional[int] = None
        self._cur_day: Optional[str] = None
        os.makedirs(base_dir, exist_ok=True)

    # -- file management ---------------------------------------------------

    def _next_file_of_day(self, ms: int) -> str:
        date = _date_str(ms)
        model = f"{self.base_name}.{date}"
        existing = [os.path.basename(p)
                    for p in list_metric_files(self.base_dir, self.base_name)
                    if os.path.basename(p).startswith(model)]
        if not existing:
            return os.path.join(self.base_dir, model)
        last = max((_file_sort_key(f)[1] for f in existing), default=0)
        return os.path.join(self.base_dir, f"{model}.{last + 1}")

    def _roll(self, ms: int) -> None:
        self._close_streams()
        self._prune()
        path = self._next_file_of_day(ms)
        self._file = open(path, "ab")
        self._idx = open(path + IDX_SUFFIX, "ab")
        self._cur_path = path
        self._cur_day = _date_str(ms)

    def _prune(self) -> None:
        files = list_metric_files(self.base_dir, self.base_name)
        while len(files) >= self.total_file_count:
            victim = files.pop(0)
            for p in (victim, victim + IDX_SUFFIX):
                try:
                    os.remove(p)
                except OSError:
                    pass

    def _close_streams(self) -> None:
        for fh in (self._file, self._idx):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        self._file = self._idx = None

    # -- public API --------------------------------------------------------

    def write(self, time_ms: int, nodes: Sequence[MetricNode]) -> None:
        """Append one second's nodes; stamps them all with ``time_ms``
        (``MetricWriter.write:120-174``)."""
        if not nodes:
            return
        with self._lock:
            for n in nodes:
                n.timestamp = time_ms
            second = time_ms // 1000
            if self._file is None or not os.path.exists(self._cur_path):
                self._roll(time_ms)
            if self._last_second is not None and second < self._last_second:
                return   # out-of-order second: drop, like the reference
            if self._last_second is None or second > self._last_second:
                if self._cur_day != _date_str(time_ms):
                    self._roll(time_ms)
                self._idx.write(_IDX_ENTRY.pack(second, self._file.tell()))
                self._idx.flush()
            for n in nodes:
                self._file.write(n.to_fat_string().encode("utf-8"))
            self._file.flush()
            if self._file.tell() >= self.single_file_size:
                self._roll(time_ms)
            self._last_second = second

    def close(self) -> None:
        with self._lock:
            self._close_streams()
