"""Metric line codec — byte-format parity with the reference so its dashboard
and tooling can read our files.

Reference: ``sentinel-core/.../node/metric/MetricNode.java:160-231`` — thin
format ``ts|resource|pass|block|success|exception|rt|occupiedPass|concurrency|
classification`` and fat format with a human date inserted after ts; ``|`` in
resource names is replaced by ``_``.
"""

from __future__ import annotations

import dataclasses
import time as _time

# ResourceTypeConstants.java
TYPE_COMMON = 0
TYPE_WEB = 1
TYPE_RPC = 2
TYPE_GATEWAY = 3
TYPE_DB = 4
TYPE_CACHE = 5

TOTAL_IN_RESOURCE_NAME = "__total_inbound_traffic__"   # Constants.java:45


@dataclasses.dataclass
class MetricNode:
    timestamp: int = 0           # ms, floor of the aggregated second
    resource: str = ""
    pass_qps: int = 0
    block_qps: int = 0
    success_qps: int = 0
    exception_qps: int = 0
    rt: int = 0                  # average rt of the second, ms
    occupied_pass_qps: int = 0
    concurrency: int = 0
    classification: int = TYPE_COMMON

    def _legal_name(self) -> str:
        return self.resource.replace("|", "_")

    def to_thin_string(self) -> str:
        return "|".join(str(x) for x in (
            self.timestamp, self._legal_name(), self.pass_qps, self.block_qps,
            self.success_qps, self.exception_qps, self.rt,
            self.occupied_pass_qps, self.concurrency, self.classification))

    def to_fat_string(self) -> str:
        date = _time.strftime("%Y-%m-%d %H:%M:%S",
                              _time.localtime(self.timestamp / 1000))
        return "|".join(str(x) for x in (
            self.timestamp, date, self._legal_name(), self.pass_qps,
            self.block_qps, self.success_qps, self.exception_qps, self.rt,
            self.occupied_pass_qps, self.concurrency,
            self.classification)) + "\n"

    @staticmethod
    def from_thin_string(line: str) -> "MetricNode":
        s = line.strip().split("|")
        n = MetricNode(timestamp=int(s[0]), resource=s[1], pass_qps=int(s[2]),
                       block_qps=int(s[3]), success_qps=int(s[4]),
                       exception_qps=int(s[5]), rt=int(s[6]))
        if len(s) >= 8:
            n.occupied_pass_qps = int(s[7])
        if len(s) >= 9:
            n.concurrency = int(s[8])
        if len(s) == 10:
            n.classification = int(s[9])
        return n

    @staticmethod
    def from_fat_string(line: str) -> "MetricNode":
        s = line.strip().split("|")
        n = MetricNode(timestamp=int(s[0]), resource=s[2], pass_qps=int(s[3]),
                       block_qps=int(s[4]), success_qps=int(s[5]),
                       exception_qps=int(s[6]), rt=int(s[7]))
        if len(s) >= 9:
            n.occupied_pass_qps = int(s[8])
        if len(s) >= 10:
            n.concurrency = int(s[9])
        if len(s) == 11:
            n.classification = int(s[10])
        return n
