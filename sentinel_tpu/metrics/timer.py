"""Per-second metric aggregation → rolling files.

Reference: ``sentinel-core/.../node/metric/MetricTimerListener.java`` — a 1 s
scheduled task (started by ``FlowRuleManager``'s static init) that snapshots
every ClusterNode (+ the global ENTRY_NODE) per whole second and hands the
nodes to ``MetricWriter``. Here the per-second read is one device gather over
the minute ring (:func:`sentinel_tpu.stats.window.bucket_snapshot`) and the
loop is a daemon thread on the runtime's clock (virtual-time friendly:
``tick()`` is callable directly in tests)."""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.metrics.writer import MetricWriter


class MetricTimerListener:
    def __init__(self, sentinel, writer: Optional[MetricWriter] = None,
                 flush_interval_sec: int = 1):
        cfg = sentinel.cfg
        self._sentinel = sentinel
        self.writer = writer or MetricWriter(
            cfg.metric_dir(), cfg.app_name,
            single_file_size=cfg.metric_log_single_size,
            total_file_count=cfg.metric_log_total_count)
        self._interval = max(flush_interval_sec, 1)
        # seconds from construction onward get written (reference: the timer
        # is started by FlowRuleManager static init, before any traffic)
        self._last_written_sec = sentinel.clock.now_ms() // 1000 - 1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Sentinel.close() stops this daemon (idempotently — stop() is
        # re-callable): no metric-timer thread leak across open/close
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)

    def tick(self) -> int:
        """Aggregate every completed-but-unwritten second up to now; → number
        of seconds written. Called by the daemon loop, or directly in tests
        driving a manual clock."""
        now_sec = self._sentinel.clock.now_ms() // 1000
        written = 0
        # catch up at most one minute ring — older buckets have been recycled
        start = max(self._last_written_sec + 1, now_sec - 59)
        for sec in range(start, now_sec):   # only COMPLETED seconds
            nodes = self._sentinel.metrics_snapshot(sec * 1000)
            if nodes:
                self.writer.write(sec * 1000, nodes)
                written += 1
            self._last_written_sec = sec
        # piggyback the breaker-transition poll (EventObserverRegistry
        # analog notifies within one tick; no-op without observers)
        check = getattr(self._sentinel, "check_breaker_transitions", None)
        if check is not None:
            check()
        # ... and the block-event log flush (obs/eventlog.py buffers
        # sampled denial records; this is their 1 s drain to disk)
        obs = getattr(self._sentinel, "obs", None)
        if obs is not None:
            obs.flush()
        return written

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._interval):
                try:
                    self.tick()
                except Exception:   # pragma: no cover — keep the daemon alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sentinel-metric-timer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.writer.close()
