"""Prometheus metric exporter (reference
``sentinel-extension/sentinel-metric-exporter``: ``MetricExporterInit`` →
``JMXMetricExporter`` exposing per-resource ``MetricBean`` MXBeans —
rebuilt as the Python ecosystem's idiom, a prometheus_client collector).

One custom collector snapshots every resource's rolling-second totals in a
single device fetch (``all_node_totals``) at scrape time — no background
thread, no per-resource device round-trips. Exposes::

    sentinel_pass_qps{resource=...}        rolling-second pass count
    sentinel_block_qps{resource=...}
    sentinel_success_qps{resource=...}
    sentinel_exception_qps{resource=...}
    sentinel_avg_rt_ms{resource=...}
    sentinel_concurrency{resource=...}     live thread/inflight count
    sentinel_breaker_state{resource=...}   0 closed / 1 open / 2 half-open

Self-telemetry families (from ``Sentinel.obs`` — obs/; absent while
``SENTINEL_OBS_DISABLE`` is set)::

    sentinel_rt_p99_ms                     entry→verdict p99 (batch tier)
    sentinel_rt_quantile_ms{quantile=...}  p50 / p95 / p99 of the same
    sentinel_request_quantile_ms{quantile=...} per-REQUEST ingest→verdict
                                           through the serving front end
    sentinel_split_route_total{route=...}  dispatch-path decisions
    sentinel_compile_cache_hits_total      program-fetch cache hits
    sentinel_compile_cache_misses_total
    sentinel_compile_cache_first_fetch_retries_total
    sentinel_block_reason_total{reason=...} denials by verdict code name
    sentinel_occupy_bookings_total{event=...} granted/carried/settled/evicted
    sentinel_pipeline_total{event=...}     depth/stall/leaked_handles/
                                           meshed_dispatch/dispatches
    sentinel_frontend_total{event=...}     enqueue/queue_depth/shed
    sentinel_frontend_flush_total{reason=...} full/deadline/idle batch cuts
    sentinel_span_ring_wraps_total         spans/links lost to ring wrap
    sentinel_flight_pinned_total           SLO-pinned trace chains
    sentinel_flight_trigger_total{kind=...} deadline_miss/shed/p99/block_burst
    sentinel_sortfree_bucket_overflow_total claim-cascade sorted fallbacks
    sentinel_tune_total{event=...}         autotuner lifecycle: config_loaded/
                                           fingerprint_fallback/knob_rejected/
                                           trial/parity_fail
    sentinel_resource_qps{resource=...}    hot-resource rolling QPS — top-K
                                           labels ONLY (obs/telemetry.py)
    sentinel_resource_rt_ms{resource=...,quantile=...}
                                           per-resource RT quantiles (p50/
                                           p95/p99) from the device-resident
                                           cumulative histogram table — top-K
                                           labels only; absent when
                                           SENTINEL_RESOURCE_HIST_DISABLE set
    sentinel_telemetry_total{event=...}    telemetry health: tick/readback_drop
                                           /hist_tick
    sentinel_exporter_label_overflow_total samples dropped at the label cap

Label-cardinality guard: the per-resource gauge families cap the number
of distinct ``resource`` label values per scrape
(:data:`LABEL_CARDINALITY_CAP`, constructor-overridable). Beyond the cap
the hottest rows (by pass+block) win, the rest are dropped and counted
(``exporter.label_overflow``) — per-resource labels can never explode
the scrape, no matter how many resources register. The telemetry family
is bounded by construction (top-K ≤ 128 labels).

Every key in the fixed counter CATALOG (obs/counters.py) has a family
here — tests/test_obs.py walks the catalog against the rendered scrape
so a key added without an export shows up as a test failure, not a
silent observability gap.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import start_http_server
from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily
from prometheus_client.registry import REGISTRY

#: Default per-family cap on distinct ``resource`` label values per
#: scrape. Prometheus guidance keeps label cardinality in the hundreds;
#: at 1M registered resources an uncapped scrape would be megabytes.
LABEL_CARDINALITY_CAP = 512


class SentinelCollector:
    """Register with ``prometheus_client``'s registry; each scrape pulls one
    consistent snapshot of all resources."""

    _GAUGES = (
        ("pass", "pass_qps", "Rolling-second pass count"),
        ("block", "block_qps", "Rolling-second block count"),
        ("success", "success_qps", "Rolling-second success count"),
        ("exception", "exception_qps", "Rolling-second exception count"),
        ("avg_rt", "avg_rt_ms", "Rolling-second average RT (ms)"),
        ("threads", "concurrency", "Live in-flight count"),
    )

    def __init__(self, sentinel, namespace: str = "sentinel",
                 label_cap: int = LABEL_CARDINALITY_CAP):
        self.sentinel = sentinel
        self.namespace = namespace
        self.label_cap = max(1, int(label_cap))

    def describe(self):
        """Static family list so Registry.register doesn't trigger a full
        collect (device snapshot + first-compile) at construction time."""
        ns = self.namespace
        for _key, suffix, doc in self._GAUGES:
            yield GaugeMetricFamily(f"{ns}_{suffix}", doc,
                                    labels=["resource"])
        yield GaugeMetricFamily(
            f"{ns}_breaker_state",
            "Circuit state: 0 closed, 1 open, 2 half-open",
            labels=["resource"])
        yield from self._obs_families(describe_only=True)

    def _obs_families(self, describe_only: bool = False):
        """Self-telemetry families (host-side reads only — no device
        work, so scrapes stay cheap even under SENTINEL_OBS_DISABLE)."""
        ns = self.namespace
        obs = getattr(self.sentinel, "obs", None)
        p99 = GaugeMetricFamily(
            f"{ns}_rt_p99_ms",
            "p99 entry→verdict latency over the batch tier (ms)")
        quant = GaugeMetricFamily(
            f"{ns}_rt_quantile_ms",
            "entry→verdict latency quantiles (ms)", labels=["quantile"])
        req_quant = GaugeMetricFamily(
            f"{ns}_request_quantile_ms",
            "per-request ingest→verdict latency quantiles through the "
            "serving front end (ms)", labels=["quantile"])
        route = CounterMetricFamily(
            f"{ns}_split_route",
            "Dispatch-path decisions by route", labels=["route"])
        hits = CounterMetricFamily(
            f"{ns}_compile_cache_hits",
            "Decide-program fetch cache hits")
        misses = CounterMetricFamily(
            f"{ns}_compile_cache_misses",
            "Decide-program fetch cache misses (first dispatches)")
        retries = CounterMetricFamily(
            f"{ns}_compile_cache_first_fetch_retries",
            "Guarded first-fetch stall retries")
        blocks = CounterMetricFamily(
            f"{ns}_block_reason",
            "Denials by verdict reason name", labels=["reason"])
        occupy = CounterMetricFamily(
            f"{ns}_occupy_bookings",
            "Priority occupy booking lifecycle events", labels=["event"])
        pipeline = CounterMetricFamily(
            f"{ns}_pipeline",
            "Dispatch-pipeline health: depth (sum of in-flight at each "
            "enqueue), stall, leaked_handles", labels=["event"])
        frontend = CounterMetricFamily(
            f"{ns}_frontend",
            "Serving front-end ingest events: enqueue, queue_depth "
            "(sum of pending depth at each enqueue), shed",
            labels=["event"])
        fe_flush = CounterMetricFamily(
            f"{ns}_frontend_flush",
            "Why each device batch was cut", labels=["reason"])
        wraps = CounterMetricFamily(
            f"{ns}_span_ring_wraps",
            "Spans/links lost to per-thread ring wrap (capacity too "
            "small for the sustained span rate)")
        flight_pinned = CounterMetricFamily(
            f"{ns}_flight_pinned",
            "Trace chains pinned by an SLO flight-recorder trigger")
        flight_trig = CounterMetricFamily(
            f"{ns}_flight_trigger",
            "Flight-recorder SLO triggers fired (post rate limiting)",
            labels=["kind"])
        sf_ovf = CounterMetricFamily(
            f"{ns}_sortfree_bucket_overflow",
            "Sort-free claim-cascade overflows (elements that fell back "
            "to the sorted branch; sustained growth = bucket table "
            "undersized for the key distribution)")
        tune = CounterMetricFamily(
            f"{ns}_tune",
            "Autotuner lifecycle: config_loaded / fingerprint_fallback "
            "/ knob_rejected at startup, trial / parity_fail during a "
            "sweep", labels=["event"])
        res_qps = GaugeMetricFamily(
            f"{ns}_resource_qps",
            "Hot-resource rolling pass+block QPS — top-K labels only "
            "(the device-merged hot set, obs/telemetry.py)",
            labels=["resource"])
        res_rt = GaugeMetricFamily(
            f"{ns}_resource_rt_ms",
            "Per-resource RT quantiles (ms) from the device-resident "
            "cumulative log-bucket histogram — top-K labels only "
            "(obs/resource_hist.py; absent when "
            "SENTINEL_RESOURCE_HIST_DISABLE is set)",
            labels=["resource", "quantile"])
        telem = CounterMetricFamily(
            f"{ns}_telemetry",
            "Hot-resource telemetry health: tick (device reads "
            "dispatched) / readback_drop (async readback fell behind) / "
            "hist_tick (hot sets landed with histogram quantiles)",
            labels=["event"])
        label_ovf = CounterMetricFamily(
            f"{ns}_exporter_label_overflow",
            "Resource-labeled scrape samples dropped at the "
            "label-cardinality cap")
        tier = CounterMetricFamily(
            f"{ns}_tier_total",
            "Tiered-state lifecycle: hot_hit / cold_miss intern "
            "classifications, promoted / demoted row migrations, "
            "sketch_overflow halvings (tiering/manager.py)",
            labels=["event"])
        control = CounterMetricFamily(
            f"{ns}_control_total",
            "Overload-controller activity: tick (control cycles), "
            "shed_rate / retune_batcher / degrade (actions applied), "
            "admission_dropped (requests shed at the admission gate), "
            "tail_signal (ticks where per-resource p99 deltas fed the "
            "degrade policy) (control/loop.py)",
            labels=["action"])
        if not describe_only and obs is not None and obs.enabled:
            from sentinel_tpu.obs import counters as ck
            counts = obs.counters.snapshot()
            v99 = obs.hist_entry.percentile_ms(0.99)
            if v99 is not None:
                p99.add_metric([], v99)
            for q in (0.50, 0.95, 0.99):
                v = obs.hist_entry.percentile_ms(q)
                if v is not None:
                    quant.add_metric([f"{q:g}"], v)
                rv = obs.hist_request.percentile_ms(q)
                if rv is not None:
                    req_quant.add_metric([f"{q:g}"], rv)
            for key, fam_key in ((ck.ROUTE_SCALAR, "scalar"),
                                 (ck.ROUTE_FAST, "fast"),
                                 (ck.ROUTE_FAST_OCCUPY, "fast_occupy"),
                                 (ck.ROUTE_GENERAL, "general_sorted"),
                                 (ck.ROUTE_SPLIT, "split_fired"),
                                 (ck.ROUTE_FUSED, "fused_exit"),
                                 (ck.ROUTE_MESHED, "meshed"),
                                 (ck.ROUTE_SORTFREE, "sortfree"),
                                 (ck.ROUTE_SINGLE_DISPATCH,
                                  "single_dispatch")):
                route.add_metric([fam_key], counts.get(key, 0))
            sf_ovf.add_metric([], counts.get(ck.SORTFREE_OVERFLOW, 0))
            hits.add_metric([], counts.get(ck.CACHE_HIT, 0))
            misses.add_metric([], counts.get(ck.CACHE_MISS, 0))
            retries.add_metric([], counts.get(ck.CACHE_RETRY, 0))
            for key, v in sorted(counts.items()):
                if key.startswith(ck.BLOCK_PREFIX):
                    blocks.add_metric([key[len(ck.BLOCK_PREFIX):]], v)
            for key, ev in ((ck.OCCUPY_GRANTED, "granted"),
                            (ck.OCCUPY_CARRIED, "carried"),
                            (ck.OCCUPY_SETTLED, "settled"),
                            (ck.OCCUPY_EVICTED, "evicted")):
                occupy.add_metric([ev], counts.get(key, 0))
            for key, ev in ((ck.PIPE_DEPTH, "depth"),
                            (ck.PIPE_STALL, "stall"),
                            (ck.PIPE_LEAKED, "leaked_handles"),
                            (ck.PIPE_MESHED, "meshed_dispatch"),
                            (ck.PIPE_DISPATCH, "dispatches")):
                pipeline.add_metric([ev], counts.get(key, 0))
            for key, ev in ((ck.FE_ENQUEUE, "enqueue"),
                            (ck.FE_QUEUE_DEPTH, "queue_depth"),
                            (ck.FE_SHED, "shed")):
                frontend.add_metric([ev], counts.get(key, 0))
            for key, reason in ((ck.FE_FLUSH_FULL, "full"),
                                (ck.FE_FLUSH_DEADLINE, "deadline"),
                                (ck.FE_FLUSH_IDLE, "idle")):
                fe_flush.add_metric([reason], counts.get(key, 0))
            wraps.add_metric([], counts.get(ck.SPAN_RING_WRAP, 0))
            flight_pinned.add_metric([], counts.get(ck.FLIGHT_PINNED, 0))
            for key, v in sorted(counts.items()):
                if key.startswith(ck.FLIGHT_TRIGGER_PREFIX):
                    flight_trig.add_metric(
                        [key[len(ck.FLIGHT_TRIGGER_PREFIX):]], v)
            for key, ev in ((ck.TUNE_LOADED, "config_loaded"),
                            (ck.TUNE_FALLBACK, "fingerprint_fallback"),
                            (ck.TUNE_KNOB_REJECTED, "knob_rejected"),
                            (ck.TUNE_TRIAL, "trial"),
                            (ck.TUNE_PARITY_FAIL, "parity_fail")):
                tune.add_metric([ev], counts.get(key, 0))
            for key, ev in ((ck.TELEMETRY_TICK, "tick"),
                            (ck.TELEMETRY_DROP, "readback_drop"),
                            (ck.TELEMETRY_HIST_TICK, "hist_tick")):
                telem.add_metric([ev], counts.get(key, 0))
            label_ovf.add_metric(
                [], counts.get(ck.EXPORTER_LABEL_OVERFLOW, 0))
            for key, ev in ((ck.TIER_HOT_HIT, "hot_hit"),
                            (ck.TIER_COLD_MISS, "cold_miss"),
                            (ck.TIER_PROMOTED, "promoted"),
                            (ck.TIER_DEMOTED, "demoted"),
                            (ck.TIER_SKETCH_OVERFLOW, "sketch_overflow")):
                tier.add_metric([ev], counts.get(key, 0))
            for key, ev in ((ck.CONTROL_TICK, "tick"),
                            (ck.CONTROL_SHED_ACTION, "shed_rate"),
                            (ck.CONTROL_RETUNE_ACTION, "retune_batcher"),
                            (ck.CONTROL_DEGRADE_ACTION, "degrade"),
                            (ck.CONTROL_DROPPED, "admission_dropped"),
                            (ck.CONTROL_TAIL_SIGNAL, "tail_signal")):
                control.add_metric([ev], counts.get(key, 0))
            # bounded by construction: at most telemetry.k ≤ MAX_K labels
            # (×3 quantile labels for res_rt — still top-K-bounded)
            telemetry = getattr(self.sentinel, "telemetry", None)
            if telemetry is not None and telemetry.enabled:
                for h in telemetry.hot_entries():
                    res_qps.add_metric([h["resource"]], float(h["qps"]))
                    for q, fld in (("0.5", "rt_p50_ms"),
                                   ("0.95", "rt_p95_ms"),
                                   ("0.99", "rt_p99_ms")):
                        if fld in h:
                            res_rt.add_metric([h["resource"], q],
                                              float(h[fld]))
        yield from (p99, quant, req_quant, route, hits, misses, retries,
                    blocks, occupy, pipeline, frontend, fe_flush, wraps,
                    flight_pinned, flight_trig, sf_ovf, tune,
                    res_qps, res_rt, telem, label_ovf, tier, control)

    def collect(self):
        ns = self.namespace
        gauges = {key: GaugeMetricFamily(f"{ns}_{suffix}", doc,
                                         labels=["resource"])
                  for key, suffix, doc in self._GAUGES}
        breaker = GaugeMetricFamily(
            f"{ns}_breaker_state",
            "Circuit state: 0 closed, 1 open, 2 half-open",
            labels=["resource"])

        totals = self.sentinel.all_node_totals()
        # label-cardinality guard: never more than label_cap distinct
        # resource labels per family — keep the hottest rows (pass+block,
        # name-tiebroken for a deterministic scrape), drop and COUNT the
        # cold tail (exporter.label_overflow)
        dropped = len(totals) - self.label_cap
        if dropped > 0:
            totals = sorted(
                totals,
                key=lambda it: (-(it[2].get("pass", 0)
                                  + it[2].get("block", 0)), it[0]),
            )[:self.label_cap]
            obs = getattr(self.sentinel, "obs", None)
            if obs is not None:
                from sentinel_tpu.obs import counters as ck
                obs.counters.add(ck.EXPORTER_LABEL_OVERFLOW, dropped)
        for name, _row, t in totals:
            for key, fam in gauges.items():
                fam.add_metric([name], float(t.get(key, 0) or 0))
        # several rules may guard one resource; one sample per label set
        # (duplicates make Prometheus reject the whole scrape) — report the
        # most-degraded state (OPEN > HALF_OPEN > CLOSED)
        by_res: dict = {}
        for res, state in self.sentinel.breaker_resources():
            rank = {0: 0, 2: 1, 1: 2}.get(state, 0)
            cur = by_res.get(res)
            if cur is None or rank > cur[0]:
                by_res[res] = (rank, state)
        for res, (_rank, state) in by_res.items():
            breaker.add_metric([res], float(state))
        yield from gauges.values()
        yield breaker
        yield from self._obs_families()


class PrometheusExporter:
    """Convenience wrapper: register the collector and (optionally) serve
    ``/metrics`` on its own port (``MetricExporterInit`` analog)."""

    def __init__(self, sentinel, *, registry=REGISTRY,
                 namespace: str = "sentinel",
                 label_cap: int = LABEL_CARDINALITY_CAP):
        self.collector = SentinelCollector(sentinel, namespace,
                                           label_cap=label_cap)
        self.registry = registry
        self._server = None
        registry.register(self.collector)
        # Sentinel.close() then unregisters the collector and releases
        # the listener — no leaked registration across open/close cycles
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)

    def serve(self, port: int = 9464, addr: str = "0.0.0.0") -> None:
        self._server, _ = start_http_server(
            port, addr=addr, registry=self.registry)

    def close(self) -> None:
        try:
            self.registry.unregister(self.collector)
        except KeyError:
            pass
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()   # release the listening socket now
            self._server = None
