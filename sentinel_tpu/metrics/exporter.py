"""Prometheus metric exporter (reference
``sentinel-extension/sentinel-metric-exporter``: ``MetricExporterInit`` →
``JMXMetricExporter`` exposing per-resource ``MetricBean`` MXBeans —
rebuilt as the Python ecosystem's idiom, a prometheus_client collector).

One custom collector snapshots every resource's rolling-second totals in a
single device fetch (``all_node_totals``) at scrape time — no background
thread, no per-resource device round-trips. Exposes::

    sentinel_pass_qps{resource=...}        rolling-second pass count
    sentinel_block_qps{resource=...}
    sentinel_success_qps{resource=...}
    sentinel_exception_qps{resource=...}
    sentinel_avg_rt_ms{resource=...}
    sentinel_concurrency{resource=...}     live thread/inflight count
    sentinel_breaker_state{resource=...}   0 closed / 1 open / 2 half-open
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import start_http_server
from prometheus_client.core import GaugeMetricFamily
from prometheus_client.registry import REGISTRY


class SentinelCollector:
    """Register with ``prometheus_client``'s registry; each scrape pulls one
    consistent snapshot of all resources."""

    _GAUGES = (
        ("pass", "pass_qps", "Rolling-second pass count"),
        ("block", "block_qps", "Rolling-second block count"),
        ("success", "success_qps", "Rolling-second success count"),
        ("exception", "exception_qps", "Rolling-second exception count"),
        ("avg_rt", "avg_rt_ms", "Rolling-second average RT (ms)"),
        ("threads", "concurrency", "Live in-flight count"),
    )

    def __init__(self, sentinel, namespace: str = "sentinel"):
        self.sentinel = sentinel
        self.namespace = namespace

    def describe(self):
        """Static family list so Registry.register doesn't trigger a full
        collect (device snapshot + first-compile) at construction time."""
        ns = self.namespace
        for _key, suffix, doc in self._GAUGES:
            yield GaugeMetricFamily(f"{ns}_{suffix}", doc,
                                    labels=["resource"])
        yield GaugeMetricFamily(
            f"{ns}_breaker_state",
            "Circuit state: 0 closed, 1 open, 2 half-open",
            labels=["resource"])

    def collect(self):
        ns = self.namespace
        gauges = {key: GaugeMetricFamily(f"{ns}_{suffix}", doc,
                                         labels=["resource"])
                  for key, suffix, doc in self._GAUGES}
        breaker = GaugeMetricFamily(
            f"{ns}_breaker_state",
            "Circuit state: 0 closed, 1 open, 2 half-open",
            labels=["resource"])

        totals = self.sentinel.all_node_totals()
        for name, _row, t in totals:
            for key, fam in gauges.items():
                fam.add_metric([name], float(t.get(key, 0) or 0))
        # several rules may guard one resource; one sample per label set
        # (duplicates make Prometheus reject the whole scrape) — report the
        # most-degraded state (OPEN > HALF_OPEN > CLOSED)
        by_res: dict = {}
        for res, state in self.sentinel.breaker_resources():
            rank = {0: 0, 2: 1, 1: 2}.get(state, 0)
            cur = by_res.get(res)
            if cur is None or rank > cur[0]:
                by_res[res] = (rank, state)
        for res, (_rank, state) in by_res.items():
            breaker.add_metric([res], float(state))
        yield from gauges.values()
        yield breaker


class PrometheusExporter:
    """Convenience wrapper: register the collector and (optionally) serve
    ``/metrics`` on its own port (``MetricExporterInit`` analog)."""

    def __init__(self, sentinel, *, registry=REGISTRY,
                 namespace: str = "sentinel"):
        self.collector = SentinelCollector(sentinel, namespace)
        self.registry = registry
        self._server = None
        registry.register(self.collector)

    def serve(self, port: int = 9464, addr: str = "0.0.0.0") -> None:
        self._server, _ = start_http_server(
            port, addr=addr, registry=self.registry)

    def close(self) -> None:
        try:
            self.registry.unregister(self.collector)
        except KeyError:
            pass
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()   # release the listening socket now
            self._server = None
