"""Prometheus metric exporter (reference
``sentinel-extension/sentinel-metric-exporter``: ``MetricExporterInit`` →
``JMXMetricExporter`` exposing per-resource ``MetricBean`` MXBeans —
rebuilt as the Python ecosystem's idiom, a prometheus_client collector).

One custom collector snapshots every resource's rolling-second totals in a
single device fetch (``all_node_totals``) at scrape time — no background
thread, no per-resource device round-trips. Exposes::

    sentinel_pass_qps{resource=...}        rolling-second pass count
    sentinel_block_qps{resource=...}
    sentinel_success_qps{resource=...}
    sentinel_exception_qps{resource=...}
    sentinel_avg_rt_ms{resource=...}
    sentinel_concurrency{resource=...}     live thread/inflight count
    sentinel_breaker_state{resource=...}   0 closed / 1 open / 2 half-open
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import start_http_server
from prometheus_client.core import GaugeMetricFamily
from prometheus_client.registry import REGISTRY


class SentinelCollector:
    """Register with ``prometheus_client``'s registry; each scrape pulls one
    consistent snapshot of all resources."""

    def __init__(self, sentinel, namespace: str = "sentinel"):
        self.sentinel = sentinel
        self.namespace = namespace

    def collect(self):
        ns = self.namespace
        gauges = {
            "pass": GaugeMetricFamily(
                f"{ns}_pass_qps", "Rolling-second pass count",
                labels=["resource"]),
            "block": GaugeMetricFamily(
                f"{ns}_block_qps", "Rolling-second block count",
                labels=["resource"]),
            "success": GaugeMetricFamily(
                f"{ns}_success_qps", "Rolling-second success count",
                labels=["resource"]),
            "exception": GaugeMetricFamily(
                f"{ns}_exception_qps", "Rolling-second exception count",
                labels=["resource"]),
            "avg_rt": GaugeMetricFamily(
                f"{ns}_avg_rt_ms", "Rolling-second average RT (ms)",
                labels=["resource"]),
            "threads": GaugeMetricFamily(
                f"{ns}_concurrency", "Live in-flight count",
                labels=["resource"]),
        }
        breaker = GaugeMetricFamily(
            f"{ns}_breaker_state",
            "Circuit state: 0 closed, 1 open, 2 half-open",
            labels=["resource"])

        totals = self.sentinel.all_node_totals()
        for name, _row, t in totals:
            for key, fam in gauges.items():
                fam.add_metric([name], float(t.get(key, 0) or 0))
        for res, state in self.sentinel.breaker_resources():
            breaker.add_metric([res], float(state))
        yield from gauges.values()
        yield breaker


class PrometheusExporter:
    """Convenience wrapper: register the collector and (optionally) serve
    ``/metrics`` on its own port (``MetricExporterInit`` analog)."""

    def __init__(self, sentinel, *, registry=REGISTRY,
                 namespace: str = "sentinel"):
        self.collector = SentinelCollector(sentinel, namespace)
        self.registry = registry
        self._server = None
        registry.register(self.collector)

    def serve(self, port: int = 9464, addr: str = "0.0.0.0") -> None:
        self._server, _ = start_http_server(
            port, addr=addr, registry=self.registry)

    def close(self) -> None:
        try:
            self.registry.unregister(self.collector)
        except KeyError:
            pass
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()   # release the listening socket now
            self._server = None
