"""Metric file pipeline: per-second aggregation → rolling files + search
(reference ``sentinel-core/.../node/metric/``, SURVEY §3.4)."""

from sentinel_tpu.metrics.node import (
    TOTAL_IN_RESOURCE_NAME,
    TYPE_CACHE,
    TYPE_COMMON,
    TYPE_DB,
    TYPE_GATEWAY,
    TYPE_RPC,
    TYPE_WEB,
    MetricNode,
)
from sentinel_tpu.metrics.searcher import MetricSearcher
from sentinel_tpu.metrics.timer import MetricTimerListener
from sentinel_tpu.metrics.writer import MetricWriter, form_metric_file_name

__all__ = [
    "MetricNode", "MetricWriter", "MetricSearcher", "MetricTimerListener",
    "form_metric_file_name", "TOTAL_IN_RESOURCE_NAME",
    "TYPE_COMMON", "TYPE_WEB", "TYPE_RPC", "TYPE_GATEWAY", "TYPE_DB",
    "TYPE_CACHE",
]
