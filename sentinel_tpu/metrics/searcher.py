"""Metric file search: seek by index, filter by time/resource.

Reference: ``sentinel-core/.../node/metric/MetricSearcher.java`` +
``MetricsReader.java`` — locate the file/offset of the first second >=
beginTime via the binary .idx, then stream fat lines until past endTime or
the line cap (the ``metric`` transport command's backing,
``SendMetricCommandHandler.java:43-86``)."""

from __future__ import annotations

import os
import struct
from typing import List, Optional

from sentinel_tpu.metrics.node import MetricNode
from sentinel_tpu.metrics.writer import IDX_SUFFIX, list_metric_files

from sentinel_tpu.metrics.writer import _IDX_ENTRY  # single on-disk format def
MAX_LINES_RETURN = 100_000   # MetricsReader.maxLinesReturn


class MetricSearcher:
    def __init__(self, base_dir: str, base_name: str):
        self.base_dir = base_dir
        self.base_name = base_name

    def _idx_offset_for(self, path: str, begin_sec: int) -> Optional[int]:
        """Byte offset of the first indexed second >= begin_sec, or None when
        the whole file is older."""
        try:
            with open(path + IDX_SUFFIX, "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            return None
        for i in range(0, len(data) - _IDX_ENTRY.size + 1, _IDX_ENTRY.size):
            sec, offset = _IDX_ENTRY.unpack_from(data, i)
            if sec >= begin_sec:
                return offset
        return None

    def _last_sec_of(self, path: str) -> Optional[int]:
        try:
            size = os.path.getsize(path + IDX_SUFFIX)
            if size < _IDX_ENTRY.size:
                return None
            with open(path + IDX_SUFFIX, "rb") as fh:
                fh.seek((size // _IDX_ENTRY.size - 1) * _IDX_ENTRY.size)
                sec, _ = _IDX_ENTRY.unpack(fh.read(_IDX_ENTRY.size))
            return sec
        except OSError:
            return None

    def find(self, begin_time_ms: int, end_time_ms: Optional[int] = None,
             identity: Optional[str] = None,
             max_lines: int = MAX_LINES_RETURN) -> List[MetricNode]:
        """All metric nodes with begin <= ts (<= end), optionally one
        resource (``findByTimeAndResource``)."""
        begin_sec = begin_time_ms // 1000
        out: List[MetricNode] = []
        for path in list_metric_files(self.base_dir, self.base_name):
            last = self._last_sec_of(path)
            if last is not None and last < begin_sec:
                continue   # entire file predates the window
            offset = self._idx_offset_for(path, begin_sec)
            if offset is None:
                offset = 0
            try:
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    for raw in fh:
                        try:
                            node = MetricNode.from_fat_string(
                                raw.decode("utf-8", "replace"))
                        except (ValueError, IndexError):
                            continue
                        if node.timestamp < begin_time_ms:
                            continue
                        if end_time_ms is not None and node.timestamp > end_time_ms:
                            return out
                        if identity is not None and node.resource != identity:
                            continue
                        out.append(node)
                        if len(out) >= max_lines:
                            return out
            except OSError:
                continue
        return out
