"""Pluggable dashboard rule pipeline — the v2
``DynamicRuleProvider`` / ``DynamicRulePublisher`` SPI.

Reference: ``sentinel-dashboard/.../rule/DynamicRuleProvider.java`` +
``DynamicRulePublisher.java`` with ``FlowRuleApiProvider``/``...ApiPublisher``
as the machine-direct defaults and config-center variants (the Nacos sample)
swapped in per rule type. Here: register a (provider, publisher) pair per
rule type on the :class:`Dashboard`; the existing CRUD endpoints then read
rules from / publish rules to the config center instead of the machines —
the agents pull the same store through a datasource
(``NacosDataSource``/``FileRefreshableDataSource``/...), closing the
dashboard → config-center → agent loop without direct pushes.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional


class DynamicRuleProvider:
    """Fetch the current rule list (plain dicts) for an app from wherever
    rules live (config center, file, ...)."""

    def get_rules(self, app: str) -> List[dict]:
        raise NotImplementedError


class DynamicRulePublisher:
    """Publish the full rule list for an app to the rule store."""

    def publish(self, app: str, rules: List[dict]) -> None:
        raise NotImplementedError


class CallbackRuleProvider(DynamicRuleProvider):
    """Adapter over any ``fetch(app) -> List[dict]`` callable."""

    def __init__(self, fetch: Callable[[str], List[dict]]):
        self._fetch = fetch

    def get_rules(self, app: str) -> List[dict]:
        return list(self._fetch(app) or [])


class CallbackRulePublisher(DynamicRulePublisher):
    """Adapter over any ``publish(app, rules)`` callable."""

    def __init__(self, push: Callable[[str, List[dict]], None]):
        self._push = push

    def publish(self, app: str, rules: List[dict]) -> None:
        self._push(app, rules)


class FileRuleStore(DynamicRuleProvider, DynamicRulePublisher):
    """Provider + publisher over one JSON file per app — the smallest real
    config center (the reference's FileWritableDataSource closed the same
    loop agent-side). Layout: ``{dir}/{app}-{rtype}-rules.json``. Agents
    watch the same file with :class:`FileRefreshableDataSource`."""

    def __init__(self, directory: str, rtype: str):
        import os

        self.directory = directory
        self.rtype = rtype
        os.makedirs(directory, exist_ok=True)

    def path_for(self, app: str) -> str:
        import os

        return os.path.join(self.directory, f"{app}-{self.rtype}-rules.json")

    def get_rules(self, app: str) -> List[dict]:
        try:
            with open(self.path_for(app), encoding="utf-8") as fh:
                return json.load(fh)
        except (FileNotFoundError, ValueError):
            return []

    def publish(self, app: str, rules: List[dict]) -> None:
        import os

        tmp = self.path_for(app) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(rules, fh, indent=1)
        os.replace(tmp, self.path_for(app))


class RulePipelineRegistry:
    """Per-rule-type (provider, publisher) pairs; absent types keep the v1
    machine-direct path (``FlowRuleApiProvider`` default semantics)."""

    def __init__(self):
        self._providers: Dict[str, DynamicRuleProvider] = {}
        self._publishers: Dict[str, DynamicRulePublisher] = {}

    def set_pipeline(self, rtype: str,
                     provider: Optional[DynamicRuleProvider],
                     publisher: Optional[DynamicRulePublisher]) -> None:
        if provider is not None:
            self._providers[rtype] = provider
        else:
            self._providers.pop(rtype, None)
        if publisher is not None:
            self._publishers[rtype] = publisher
        else:
            self._publishers.pop(rtype, None)

    def provider(self, rtype: str) -> Optional[DynamicRuleProvider]:
        return self._providers.get(rtype)

    def publisher(self, rtype: str) -> Optional[DynamicRulePublisher]:
        return self._publishers.get(rtype)
