"""Machine discovery from heartbeats (reference
``sentinel-dashboard/.../discovery/{AppManagement,SimpleMachineDiscovery}.java``).

Apps are keyed by name; each machine is keyed by ``(ip, port)`` and carries
the timestamp of its last heartbeat. Health = heartbeat age below a cutoff
(the reference UI greys machines out after 60s and the metric fetcher skips
them — ``AppInfo.isHealthy`` analog).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

HEALTH_TIMEOUT_MS = 60_000


@dataclasses.dataclass
class MachineInfo:
    app: str = ""
    hostname: str = ""
    ip: str = ""
    port: int = 8719
    app_type: int = 0
    version: str = ""               # agent framework version
    heartbeat_version: int = 0      # agent-side timestamp from the beat
    last_heartbeat_ms: int = 0      # dashboard-side receive time
    exporter_port: int = 0          # Prometheus scrape port; 0 = none

    def key(self) -> str:
        return f"{self.ip}:{self.port}"

    def healthy(self, now_ms: Optional[int] = None,
                timeout_ms: int = HEALTH_TIMEOUT_MS) -> bool:
        now = int(time.time() * 1000) if now_ms is None else now_ms
        return now - self.last_heartbeat_ms < timeout_ms

    def to_dict(self, now_ms: Optional[int] = None) -> dict:
        return {
            "app": self.app, "hostname": self.hostname, "ip": self.ip,
            "port": self.port, "appType": self.app_type,
            "version": self.version,
            "heartbeatVersion": self.heartbeat_version,
            "lastHeartbeat": self.last_heartbeat_ms,
            "exporterPort": self.exporter_port,
            "healthy": self.healthy(now_ms),
        }


class AppManagement:
    """app name → {machine key → MachineInfo}; thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._apps: Dict[str, Dict[str, MachineInfo]] = {}

    def register(self, machine: MachineInfo) -> None:
        with self._lock:
            self._apps.setdefault(machine.app, {})[machine.key()] = machine

    def app_names(self) -> List[str]:
        with self._lock:
            return sorted(self._apps)

    def machines(self, app: str) -> List[MachineInfo]:
        with self._lock:
            return list(self._apps.get(app, {}).values())

    def healthy_machines(self, app: str,
                         now_ms: Optional[int] = None) -> List[MachineInfo]:
        return [m for m in self.machines(app) if m.healthy(now_ms)]

    def first_healthy(self, app: str,
                      now_ms: Optional[int] = None) -> Optional[MachineInfo]:
        ms = self.healthy_machines(app, now_ms)
        return ms[0] if ms else None

    def get_machine(self, app: str, ip: str, port: int) -> Optional[MachineInfo]:
        with self._lock:
            return self._apps.get(app, {}).get(f"{ip}:{port}")

    def remove_machine(self, app: str, ip: str, port: int) -> bool:
        """``AppManagement.removeMachine`` (AppController machine/remove
        flow); drops the app entirely when its last machine goes."""
        with self._lock:
            machines = self._apps.get(app)
            if machines is None or machines.pop(f"{ip}:{port}", None) is None:
                return False
            if not machines:
                self._apps.pop(app, None)
            return True

    def remove_app(self, app: str) -> None:
        with self._lock:
            self._apps.pop(app, None)
