"""Metric fetcher: poll every healthy machine's ``/metric`` command and
aggregate into the in-memory repository (reference
``sentinel-dashboard/.../metric/MetricFetcher.java:72-183``).

Per app, the fetcher tracks the last fetched second and pulls the window
``[last, now - DELAY]`` (metrics for the current second are still being
written agent-side) and merges lines from all machines by ``(resource, ts)``.
The agent's ``metric`` command already hides the synthetic
``__total_inbound_traffic__`` row unless requested by name
(``SendMetricCommandHandler`` behavior), so per-resource charts never see it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from sentinel_tpu.dashboard.client import AgentUnreachable, SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement
from sentinel_tpu.dashboard.repository import (
    InMemoryMetricsRepository, MetricEntity,
)

FETCH_INTERVAL_S = 6          # MetricFetcher.java:66 FETCH_INTERVAL_SECOND
DELAY_MS = 2_000              # stay behind "now" so agent seconds are closed
MAX_SPAN_MS = 60_000          # cap one pull to a minute of backlog


class MetricFetcher:
    def __init__(self, apps: AppManagement, repo: InMemoryMetricsRepository,
                 client: Optional[SentinelApiClient] = None,
                 clock=None):
        self.apps = apps
        self.repo = repo
        self.client = client or SentinelApiClient()
        self._clock = clock
        self._last_fetch_ms: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _now_ms(self) -> int:
        return (self._clock.now_ms() if self._clock is not None
                else int(time.time() * 1000))

    def fetch_once(self, app: str) -> int:
        """Pull one window for ``app``; returns entities saved."""
        now = self._now_ms()
        end = (now - DELAY_MS) // 1000 * 1000
        start = self._last_fetch_ms.get(app, end - FETCH_INTERVAL_S * 1000)
        if end - start > MAX_SPAN_MS:
            start = end - MAX_SPAN_MS
        if end <= start:
            return 0
        # (resource, ts) -> MetricEntity accumulated over machines
        agg: Dict[tuple, MetricEntity] = {}
        for m in self.apps.healthy_machines(app, now):
            try:
                nodes = self.client.fetch_metrics(m.ip, m.port, start, end - 1)
            except AgentUnreachable:
                continue
            for n in nodes:
                key = (n.resource, n.timestamp)
                e = agg.get(key)
                if e is None:
                    agg[key] = MetricEntity(
                        app=app, timestamp=n.timestamp, resource=n.resource,
                        pass_qps=n.pass_qps, block_qps=n.block_qps,
                        success_qps=n.success_qps,
                        exception_qps=n.exception_qps,
                        rt=float(n.rt), count=1)
                else:
                    total = e.count + 1
                    e.rt = (e.rt * e.count + n.rt) / total
                    e.count = total
                    e.pass_qps += n.pass_qps
                    e.block_qps += n.block_qps
                    e.success_qps += n.success_qps
                    e.exception_qps += n.exception_qps
        self.repo.save_all(list(agg.values()), now)
        self._last_fetch_ms[app] = end
        return len(agg)

    def fetch_all_once(self) -> int:
        return sum(self.fetch_once(app) for app in self.apps.app_names())

    def start(self, interval_s: float = FETCH_INTERVAL_S) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.fetch_all_once()
                except Exception:       # keep the poller alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dashboard-metric-fetcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
