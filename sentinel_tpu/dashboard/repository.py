"""In-memory stores: metrics ring + rule repositories (reference
``sentinel-dashboard/.../repository/metric/InMemoryMetricsRepository.java:40-63``
and ``repository/rule/InMemoryRuleRepositoryAdapter.java``).

Metrics are kept per ``app → resource → ordered {ts → MetricEntity}`` with a
5-minute retention window (``MAX_METRIC_LIVE_TIME_MS``); rules live in a
per-type store with a global auto-increment id, mirroring the dashboard's
``InMemFlowRuleStore`` family.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

MAX_METRIC_LIVE_TIME_MS = 5 * 60 * 1000   # InMemoryMetricsRepository.java:43


@dataclasses.dataclass
class MetricEntity:
    app: str = ""
    timestamp: int = 0          # ms, whole second
    resource: str = ""
    pass_qps: int = 0
    block_qps: int = 0
    success_qps: int = 0
    exception_qps: int = 0
    rt: float = 0.0             # avg rt for the second
    count: int = 0              # number of machines aggregated

    def to_dict(self) -> dict:
        return {
            "app": self.app, "timestamp": self.timestamp,
            "resource": self.resource, "passQps": self.pass_qps,
            "blockQps": self.block_qps, "successQps": self.success_qps,
            "exceptionQps": self.exception_qps, "rt": round(self.rt, 2),
            "count": self.count,
        }


class InMemoryMetricsRepository:
    def __init__(self, *, retention_ms: int = MAX_METRIC_LIVE_TIME_MS):
        self._lock = threading.Lock()
        self.retention_ms = retention_ms
        # app -> resource -> OrderedDict[ts -> MetricEntity]
        self._data: Dict[str, Dict[str, "OrderedDict[int, MetricEntity]"]] = {}

    def save(self, e: MetricEntity, now_ms: Optional[int] = None) -> None:
        now = int(time.time() * 1000) if now_ms is None else now_ms
        with self._lock:
            ring = (self._data.setdefault(e.app, {})
                    .setdefault(e.resource, OrderedDict()))
            old = ring.get(e.timestamp)
            if old is not None:
                # second machine reporting the same second: accumulate
                total = old.count + e.count if (old.count and e.count) else 0
                old.rt = ((old.rt * old.count + e.rt * e.count) / total
                          if total else max(old.rt, e.rt))
                old.pass_qps += e.pass_qps
                old.block_qps += e.block_qps
                old.success_qps += e.success_qps
                old.exception_qps += e.exception_qps
                old.count = total or old.count
            else:
                ring[e.timestamp] = e
            cutoff = now - self.retention_ms
            while ring and next(iter(ring)) < cutoff:
                ring.popitem(last=False)

    def save_all(self, entities: List[MetricEntity],
                 now_ms: Optional[int] = None) -> None:
        for e in entities:
            self.save(e, now_ms)

    def query(self, app: str, resource: str, start_ms: int,
              end_ms: int) -> List[MetricEntity]:
        with self._lock:
            ring = self._data.get(app, {}).get(resource, OrderedDict())
            return [e for ts, e in ring.items() if start_ms <= ts <= end_ms]

    def list_resources(self, app: str) -> List[str]:
        """Resources of ``app`` sorted by recent pass+block volume desc
        (``listResourcesOfApp`` — last minute, then alphabetical)."""
        with self._lock:
            rings = self._data.get(app, {})
            volume = {}
            for res, ring in rings.items():
                if not ring:
                    continue
                last_ts = next(reversed(ring))
                cutoff = last_ts - 60_000
                volume[res] = sum(e.pass_qps + e.block_qps
                                  for ts, e in ring.items() if ts >= cutoff)
        return sorted(volume, key=lambda r: (-volume[r], r))


@dataclasses.dataclass
class RuleEntity:
    id: int = 0
    app: str = ""
    ip: str = ""
    port: int = 0
    rule: Dict[str, Any] = dataclasses.field(default_factory=dict)
    gmt_create: int = 0

    def to_dict(self) -> dict:
        d = dict(self.rule)
        d.update(id=self.id, app=self.app, ip=self.ip, port=self.port)
        return d


class RuleRepository:
    """One store per rule type; ids are unique across types (shared counter
    like the dashboard's ``InMemoryRuleRepositoryAdapter`` ids)."""

    _ids = itertools.count(1)
    _ids_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_id: Dict[int, RuleEntity] = {}

    @classmethod
    def next_id(cls) -> int:
        with cls._ids_lock:
            return next(cls._ids)

    def save(self, entity: RuleEntity) -> RuleEntity:
        with self._lock:
            if not entity.id:
                entity.id = self.next_id()
            if not entity.gmt_create:
                entity.gmt_create = int(time.time() * 1000)
            self._by_id[entity.id] = entity
            return entity

    def save_all(self, entities: List[RuleEntity]) -> List[RuleEntity]:
        return [self.save(e) for e in entities]

    def replace_app(self, app: str, entities: List[RuleEntity]) -> List[RuleEntity]:
        """Swap the full rule set of one app (used when re-pulling from a
        machine: ``FlowControllerV1.apiQueryMachineRules`` saveAll path)."""
        with self._lock:
            for rid in [i for i, e in self._by_id.items() if e.app == app]:
                del self._by_id[rid]
        return self.save_all(entities)

    def find(self, rule_id: int) -> Optional[RuleEntity]:
        with self._lock:
            return self._by_id.get(rule_id)

    def find_by_app(self, app: str) -> List[RuleEntity]:
        with self._lock:
            return sorted((e for e in self._by_id.values() if e.app == app),
                          key=lambda e: e.id)

    def delete(self, rule_id: int) -> Optional[RuleEntity]:
        with self._lock:
            return self._by_id.pop(rule_id, None)
