"""Simple session auth (reference ``sentinel-dashboard/.../auth/``:
``SimpleWebAuthServiceImpl`` + ``LoginAuthenticationFilter`` — a single
configured user, session-cookie based, with ``/registry/machine`` and login
endpoints exempt).

Credentials default to ``sentinel``/``sentinel`` like the reference
(``auth.username``/``auth.password`` properties); empty password disables
auth entirely (the reference's ``NoOpAuthServiceImpl`` profile).
"""

from __future__ import annotations

import secrets
import threading
import time
from typing import Dict, Optional

SESSION_TTL_S = 2 * 3600

EXEMPT_PREFIXES = ("/registry/machine", "/auth/login", "/auth/check",
                   "/static/", "/favicon.ico")


class AuthService:
    def __init__(self, username: str = "sentinel",
                 password: str = "sentinel"):
        self.username = username
        self.password = password
        self._lock = threading.Lock()
        self._sessions: Dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.password)

    def login(self, username: str, password: str) -> Optional[str]:
        if username != self.username or password != self.password:
            return None
        token = secrets.token_urlsafe(24)
        with self._lock:
            self._sessions[token] = time.time() + SESSION_TTL_S
        return token

    def logout(self, token: str) -> None:
        with self._lock:
            self._sessions.pop(token, None)

    def check(self, token: Optional[str]) -> bool:
        if not self.enabled:
            return True
        if not token:
            return False
        with self._lock:
            exp = self._sessions.get(token)
            if exp is None:
                return False
            if exp < time.time():
                del self._sessions[token]
                return False
            return True

    def exempt(self, path: str) -> bool:
        return path == "/" or path.endswith(".html") or any(
            path.startswith(p) for p in EXEMPT_PREFIXES)
