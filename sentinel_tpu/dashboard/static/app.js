"use strict";
/* Operator dashboard SPA (reference: sentinel-dashboard AngularJS webapp —
   app list, machine discovery, realtime per-resource charts, rule editors
   for every rule family, cluster topology/assign — rebuilt dependency-free
   against the Python dashboard's REST surface). */

// ------------------------------------------------------------------ helpers
const $ = (sel) => document.querySelector(sel);

function h(tag, attrs = {}, children = []) {
  const e = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "class") e.className = v;
    else if (k.startsWith("on")) e[k] = v;
    else if (k === "html") e.innerHTML = v;
    else e.setAttribute(k, v);
  }
  for (const c of [].concat(children)) {
    if (c == null) continue;
    e.appendChild(typeof c === "string" ? document.createTextNode(c) : c);
  }
  return e;
}

async function api(path, opts) {
  const r = await fetch(path, opts);
  const j = await r.json();
  if (j && j.code === 401) { showLogin(true); return null; }
  return j;
}
const post = (path, body, method = "POST") => api(path, {
  method, body: body === undefined ? undefined : JSON.stringify(body),
  headers: { "Content-Type": "application/json" } });

function getPath(obj, path) {
  return path.split(".").reduce((o, k) => (o == null ? o : o[k]), obj);
}
function setPath(obj, path, v) {
  const ks = path.split("."), last = ks.pop();
  let o = obj;
  for (const k of ks) o = (o[k] = o[k] || {});
  o[last] = v;
}

// ------------------------------------------------------------------ state
const S = {
  apps: [], app: null, view: "metrics", timer: null,
  machines: [], machineSel: "", range: 300, chartData: {},
  openOrigins: new Set(),   // resources with the origin drill-down expanded
};

function setRefresh(fn, ms) {
  clearInterval(S.timer);
  if (fn) { S.timer = setInterval(fn, ms); }
}

// ------------------------------------------------------------------ auth
function showLogin(on) {
  $("#login").style.display = on ? "" : "none";
  $("#app").style.display = on ? "none" : "flex";
  if (on) setRefresh(null);
}
async function doLogin(ev) {
  ev.preventDefault();
  // raw fetch: api() would swallow the 401 envelope of a bad password
  const r = await fetch("/auth/login", { method: "POST",
    body: JSON.stringify({ username: $("#u").value, password: $("#p").value }),
    headers: { "Content-Type": "application/json" } });
  const j = await r.json();
  if (!j.success) { $("#lerr").textContent = j.msg; return false; }
  $("#who").textContent = j.data.username;
  showLogin(false); boot();
  return false;
}
async function doLogout() {
  await post("/auth/logout", {});
  showLogin(true);
}

// ------------------------------------------------------------------ router
const RULE_VIEWS = ["flow", "degrade", "paramFlow", "system", "authority",
                    "gatewayFlow", "gatewayApi"];
const VIEW_TITLES = {
  metrics: "Realtime Metrics", resources: "Resource View",
  machines: "Machine List", cluster: "Cluster Management",
  tree: "Node Tree", telemetry: "Runtime Telemetry",
  hotkeys: "Hot Resources", control: "Overload Control",
  flow: "Flow Rules", degrade: "Degrade Rules", paramFlow: "Param Flow Rules",
  system: "System Rules", authority: "Authority Rules",
  gatewayFlow: "Gateway Flow Rules", gatewayApi: "API Definitions",
};

function nav(app, view) {
  location.hash = `#/${encodeURIComponent(app)}/${view}`;
}
function route() {
  const m = location.hash.match(/^#\/([^/]+)\/([^/]+)/);
  if (m) {
    S.app = decodeURIComponent(m[1]);
    S.view = VIEW_TITLES[m[2]] ? m[2] : "metrics";
  }
  render();
}
window.addEventListener("hashchange", route);

// ------------------------------------------------------------------ boot
async function boot() {
  const j = await api("/app/names.json");
  if (!j) return;
  S.apps = j.data || [];
  if (!S.app || !S.apps.includes(S.app)) S.app = S.apps[0] || null;
  route();
}

// ------------------------------------------------------------------ sidebar
function renderSidebar() {
  const navEl = $("#nav");
  navEl.innerHTML = "";
  navEl.appendChild(h("h4", {}, "Applications"));
  for (const a of S.apps) {
    navEl.appendChild(h("div", {
      class: "item" + (a === S.app ? " sel" : ""),
      onclick: () => nav(a, S.view) }, a));
  }
  if (!S.app) {
    navEl.appendChild(h("div", { class: "dim" },
      "no apps yet — waiting for heartbeats"));
    return;
  }
  const menu = [["metrics", "Realtime Metrics"], ["resources", "Resource View"],
                ["tree", "Node Tree"], ["telemetry", "Telemetry"],
                ["hotkeys", "Hot Resources"],
                ["control", "Overload Control"],
                ["machines", "Machine List"], ["cluster", "Cluster"]];
  navEl.appendChild(h("h4", {}, "Monitor"));
  for (const [v, label] of menu) {
    navEl.appendChild(h("div", {
      class: "item" + (v === S.view ? " sel" : ""),
      onclick: () => nav(S.app, v) }, label));
  }
  navEl.appendChild(h("h4", {}, "Rules"));
  for (const v of RULE_VIEWS) {
    navEl.appendChild(h("div", {
      class: "item" + (v === S.view ? " sel" : ""),
      onclick: () => nav(S.app, v) }, VIEW_TITLES[v]));
  }
}

// ------------------------------------------------------------------ render
function render() {
  renderSidebar();
  const c = $("#content");
  c.innerHTML = "";
  setRefresh(null);
  if (!S.app) { c.appendChild(h("div", { class: "empty" }, "No applications registered. Start an agent with a HeartbeatSender pointed at this dashboard.")); return; }
  if (S.view === "metrics") return viewMetrics(c);
  if (S.view === "resources") return viewResources(c);
  if (S.view === "machines") return viewMachines(c);
  if (S.view === "cluster") return viewCluster(c);
  if (S.view === "tree") return viewTree(c);
  if (S.view === "telemetry") return viewTelemetry(c);
  if (S.view === "hotkeys") return viewHotKeys(c);
  if (S.view === "control") return viewControl(c);
  return viewRules(c, S.view);
}

// ------------------------------------------------------------------ charts
function drawChart(cv, pts, hover) {
  const dpr = window.devicePixelRatio || 1;
  const W = cv.width = cv.clientWidth * dpr, H = cv.height = 170 * dpr;
  const ctx = cv.getContext("2d");
  ctx.clearRect(0, 0, W, H);
  const padL = 44 * dpr, padR = 44 * dpr, padT = 8 * dpr, padB = 20 * dpr;
  const plotW = W - padL - padR, plotH = H - padT - padB;
  ctx.font = `${11 * dpr}px system-ui`;
  if (!pts.length) {
    ctx.fillStyle = "#7f8ea0";
    ctx.fillText("no data in range", padL, H / 2);
    return null;
  }
  const qMax = Math.max(1, ...pts.map(e => Math.max(e.passQps, e.blockQps)));
  const rMax = Math.max(1, ...pts.map(e => e.rt));
  const t0 = pts[0].timestamp, t1 = pts[pts.length - 1].timestamp;
  const x = (t) => padL + (t1 === t0 ? plotW / 2
                                     : (t - t0) * plotW / (t1 - t0));
  const yQ = (v) => padT + plotH - v * plotH / qMax;
  const yR = (v) => padT + plotH - v * plotH / rMax;
  // gridlines + axes labels
  ctx.strokeStyle = "#2a3442"; ctx.fillStyle = "#7f8ea0";
  ctx.lineWidth = 1;
  for (let i = 0; i <= 4; i++) {
    const gy = padT + plotH * i / 4;
    ctx.beginPath(); ctx.moveTo(padL, gy); ctx.lineTo(W - padR, gy);
    ctx.stroke();
    ctx.textAlign = "right";
    ctx.fillText(String(Math.round(qMax * (4 - i) / 4)), padL - 5 * dpr,
                 gy + 4 * dpr);
    ctx.textAlign = "left";
    ctx.fillText(String(Math.round(rMax * (4 - i) / 4)), W - padR + 5 * dpr,
                 gy + 4 * dpr);
  }
  // x time labels
  ctx.textAlign = "center";
  for (let i = 0; i <= 3; i++) {
    const t = t0 + (t1 - t0) * i / 3;
    ctx.fillText(new Date(t).toTimeString().slice(0, 8),
                 x(t), H - 5 * dpr);
  }
  const line = (key, color, yf) => {
    ctx.beginPath(); ctx.strokeStyle = color; ctx.lineWidth = 2 * dpr;
    pts.forEach((e, i) => i ? ctx.lineTo(x(e.timestamp), yf(e[key]))
                            : ctx.moveTo(x(e.timestamp), yf(e[key])));
    ctx.stroke();
  };
  line("passQps", "#3fb97f", yQ);
  line("blockQps", "#e06c5c", yQ);
  line("rt", "#4da3ff", yR);
  if (hover != null) {
    const hx = x(hover.timestamp);
    ctx.strokeStyle = "#7f8ea0"; ctx.lineWidth = 1;
    ctx.setLineDash([3 * dpr, 3 * dpr]);
    ctx.beginPath(); ctx.moveTo(hx, padT); ctx.lineTo(hx, padT + plotH);
    ctx.stroke(); ctx.setLineDash([]);
  }
  return { x, t0, t1, padL, padR, dpr };
}

function attachTooltip(cv, getPts) {
  cv.onmousemove = (ev) => {
    const pts = getPts();
    if (!pts.length) return;
    const rect = cv.getBoundingClientRect();
    const dpr = window.devicePixelRatio || 1;
    const mx = (ev.clientX - rect.left) * dpr;
    let best = null, bestD = Infinity;
    const geo = drawChart(cv, pts, null);
    if (!geo) return;
    for (const p of pts) {
      const d = Math.abs(geo.x(p.timestamp) - mx);
      if (d < bestD) { bestD = d; best = p; }
    }
    drawChart(cv, pts, best);
    let tip = $("#tooltip");
    if (!tip) { tip = h("div", { id: "tooltip", class: "tooltip" }); document.body.appendChild(tip); }
    tip.innerHTML =
      `<b>${new Date(best.timestamp).toTimeString().slice(0, 8)}</b><br>` +
      `pass ${best.passQps} · block ${best.blockQps} · ` +
      `ok ${best.successQps} · err ${best.exceptionQps}<br>` +
      `rt ${best.rt} ms` + (best.count > 1 ? ` · ${best.count} machines` : "");
    tip.style.left = (ev.clientX + 14) + "px";
    tip.style.top = (ev.clientY + 10) + "px";
    tip.style.display = "";
  };
  cv.onmouseleave = () => {
    const tip = $("#tooltip");
    if (tip) tip.style.display = "none";
    drawChart(cv, getPts(), null);
  };
}

// ------------------------------------------------------------------ metrics
async function viewMetrics(c) {
  const head = h("div", { class: "card" }, [
    h("h3", {}, [
      h("span", {}, `Realtime Metrics — ${S.app}`),
      h("span", { class: "toolbar" }, [
        h("span", { class: "legend", html:
          '<i style="background:#3fb97f"></i>pass' +
          '<i style="background:#e06c5c"></i>block' +
          '<i style="background:#4da3ff"></i>rt (ms, right axis)' }),
        (() => {
          const sel = h("select", { onchange: (e) => {
            S.range = +e.target.value; refresh(); } },
            [[60, "last 1 min"], [300, "last 5 min"]].map(([v, l]) =>
              h("option", v === S.range ? { value: v, selected: "" }
                                        : { value: v }, l)));
          return sel;
        })(),
      ]),
    ]),
  ]);
  const box = h("div", {});
  c.appendChild(head); c.appendChild(box);
  const cards = {};   // resource -> {cv}
  async function refresh() {
    const j = await api(`/metric/resources.json?app=${encodeURIComponent(S.app)}`);
    if (!j) return;
    const end = Date.now(), start = end - S.range * 1000;
    const resources = (j.data || []).slice(0, 12);
    if (!resources.length && !box.childElementCount) {
      box.appendChild(h("div", { class: "empty" },
        "no metrics yet — traffic appears here within ~10 s of the fetcher polling agents"));
    }
    for (const res of resources) {
      if (!cards[res]) {
        const cv = h("canvas", { class: "chart" });
        box.appendChild(h("div", { class: "card" }, [
          h("h3", {}, [h("span", {}, res)]), cv]));
        cards[res] = { cv };
        attachTooltip(cv, () => S.chartData[res] || []);
      }
      const m = await api(`/metric/queryByAppAndResource.json?app=${encodeURIComponent(S.app)}&identity=${encodeURIComponent(res)}&startTime=${start}&endTime=${end}`);
      if (m) {
        S.chartData[res] = m.data || [];
        drawChart(cards[res].cv, S.chartData[res], null);
      }
    }
  }
  await refresh();
  setRefresh(refresh, 5000);
}

// ------------------------------------------------------------------ machines
function heartbeatAge(m) {
  return Math.max(0, Math.round((Date.now() - m.lastHeartbeat) / 1000));
}
async function loadMachines() {
  const j = await api(`/app/${encodeURIComponent(S.app)}/machines.json`);
  S.machines = j ? (j.data || []) : [];
  return S.machines;
}
async function viewMachines(c) {
  const tbody = h("tbody", {});
  const sysBox = h("div", {});
  c.appendChild(sysBox);
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Machines — ${S.app}`)]),
    h("table", {}, [h("thead", {}, h("tr", {}, [
      "hostname", "ip:port", "sentinel version", "heartbeat age", "status",
      "",
    ].map(t => h("th", {}, t)))), tbody]),
  ]));
  async function refreshSystem() {
    // adaptive-protection live gauges per healthy machine (systemStatus);
    // fetched in parallel so one slow agent can't stall the rest
    const healthy = S.machines.filter(x => x.healthy);
    const results = await Promise.all(healthy.map(m =>
      api(`/systemStatus.json?ip=${m.ip}&port=${m.port}`)));
    const rows = [];
    for (let i = 0; i < healthy.length; i++) {
      const m = healthy[i], j = results[i];
      if (!j || !j.success || !j.data) continue;
      const s = j.data;
      rows.push(h("tr", {}, [
        h("td", {}, `${m.ip}:${m.port}`),
        h("td", { class: "num" }, String(s.qps ?? "—")),
        h("td", { class: "num" }, String(s.thread ?? "—")),
        h("td", { class: "num" },
          s.rt != null ? Number(s.rt).toFixed(2) : "—"),
        h("td", { class: "num" },
          s.load != null && s.load >= 0 ? s.load.toFixed(2) : "—"),
        h("td", { class: "num" },
          s.cpuUsage != null && s.cpuUsage >= 0
            ? (s.cpuUsage * 100).toFixed(1) + " %" : "—"),
      ]));
    }
    sysBox.innerHTML = "";
    if (rows.length) {
      sysBox.appendChild(h("div", { class: "card" }, [
        h("h3", {}, [h("span", {}, "System status"),
          h("span", { class: "sub" },
            "inbound QPS · concurrency · avg RT · load1 · CPU (SystemSlot inputs)")]),
        h("table", {}, [h("thead", {}, h("tr", {},
          ["machine", "qps", "threads", "rt ms", "load1", "cpu"].map(t =>
            h("th", {}, t)))), h("tbody", {}, rows)]),
      ]));
    }
  }
  async function refresh() {
    await loadMachines();
    refreshSystem();
    tbody.innerHTML = "";
    for (const m of S.machines) {
      tbody.appendChild(h("tr", {}, [
        h("td", {}, m.hostname || "—"),
        h("td", {}, `${m.ip}:${m.port}`),
        h("td", {}, m.version || "—"),
        h("td", {}, `${heartbeatAge(m)} s ago`),
        h("td", {}, h("span", {
          class: "badge " + (m.healthy ? "ok" : "bad") },
          m.healthy ? "healthy" : "lost")),
        h("td", {}, h("button", { class: "sm danger", onclick: async () => {
          if (!confirm(`Remove ${m.ip}:${m.port}? It re-registers on its next heartbeat if still alive.`)) return;
          await post(`/app/${encodeURIComponent(S.app)}/machine/remove.json`,
                     { ip: m.ip, port: m.port });
          refresh();
        } }, "remove")),
      ]));
    }
    if (!S.machines.length) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 6, class: "dim" },
        "no machines")));
    }
  }
  await refresh();
  setRefresh(refresh, 5000);
}

// ------------------------------------------------------------------ telemetry
// Runtime self-telemetry (agent `obs` command → /obs/telemetry.json):
// decision counters, latency histograms, recent spans + block events
// (docs/OBSERVABILITY.md).
async function viewTelemetry(c) {
  await loadMachines();
  const sel = machineSelector(() => refresh());
  const body = h("div", {});
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Runtime Telemetry — ${S.app}`),
                 h("span", { class: "toolbar" }, [
                   h("span", { class: "sub" }, "machine"), sel])]),
    body,
  ]));
  const fmtMs = (v) => v == null ? "—" : Number(v).toFixed(3);
  const fmtUs = (ns) => (ns / 1000).toFixed(1) + " µs";
  function histRows(label, s) {
    if (!s) return null;
    return h("tr", {}, [
      h("td", {}, label),
      h("td", { class: "num" }, String(s.count ?? 0)),
      h("td", { class: "num" }, fmtMs(s.p50_ms)),
      h("td", { class: "num" }, fmtMs(s.p95_ms)),
      h("td", { class: "num" }, fmtMs(s.p99_ms)),
      h("td", { class: "num" },
        s.max_ns != null ? fmtMs(s.max_ns / 1e6) : "—"),
    ]);
  }
  function counterTable(title, sub, rows) {
    return h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, title),
                   h("span", { class: "sub" }, sub)]),
      rows.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            ["counter", "count"].map(t => h("th", {}, t)))),
            h("tbody", {}, rows.map(([k, v]) => h("tr", {}, [
              h("td", {}, k),
              h("td", { class: "num" }, String(v))])))])
        : h("span", { class: "dim" }, "no events yet"),
    ]);
  }
  async function refresh() {
    if (!S.machineSel) {
      body.innerHTML = "";
      body.appendChild(h("span", { class: "dim" }, "no healthy machine"));
      return;
    }
    const [ip, port] = S.machineSel.split(":");
    const j = await api(`/obs/telemetry.json?ip=${ip}&port=${port}`);
    body.innerHTML = "";
    if (!j || !j.success) {
      body.appendChild(h("span", { class: "bad" }, j ? j.msg : "error"));
      return;
    }
    const d = j.data || {};
    if (!d.enabled) {
      body.appendChild(h("span", { class: "dim" },
        "observability disabled on this agent (SENTINEL_OBS_DISABLE)"));
      return;
    }
    body.appendChild(h("span", { class: "sub" },
      `sampling 1/${Math.max(1, Math.round(1 / (d.sample || 1)))} · ` +
      `host threads elided: ${d.threadsElided ? "yes" : "no"}`));
    const hist = d.hist || {};
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Latency"),
        h("span", { class: "sub" },
          "log-bucketed histograms (obs/hist.py) — ms")]),
      h("table", {}, [h("thead", {}, h("tr", {},
        ["stage", "count", "p50", "p95", "p99", "max"].map(t =>
          h("th", {}, t)))),
        h("tbody", {}, [
          histRows("entry → verdict", hist.entry_to_verdict),
          histRows("dispatch device time", hist.dispatch_device),
        ])]),
    ]));
    const counts = d.counters || {};
    const groups = { "split_route.": [], "compile_cache.": [],
                     "occupy.": [], "block_reason.": [] };
    for (const k of Object.keys(counts).sort()) {
      for (const p of Object.keys(groups)) {
        if (k.startsWith(p)) groups[p].push([k.slice(p.length), counts[k]]);
      }
    }
    body.appendChild(counterTable("Split routing",
      "dispatch-path decisions per batch", groups["split_route."]));
    body.appendChild(counterTable("Compile cache",
      "decide-program fetch hits/misses/retries", groups["compile_cache."]));
    body.appendChild(counterTable("Occupy bookings",
      "priority occupy lifecycle", groups["occupy."]));
    body.appendChild(counterTable("Block reasons",
      "denials by verdict code name", groups["block_reason."]));
    const spans = d.spans || [];
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Recent spans"),
        h("span", { class: "sub" },
          "sampled batch-lifecycle traces (newest last)")]),
      spans.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            ["trace", "span", "duration", "rows", "note"].map(t =>
              h("th", {}, t)))),
            h("tbody", {}, spans.slice(-40).map(s => h("tr", {}, [
              h("td", { class: "num" }, String(s.trace)),
              h("td", {}, s.name),
              h("td", { class: "num" }, fmtUs(s.dur_ns)),
              h("td", { class: "num" }, String(s.n || "")),
              h("td", { class: "dim" }, s.note || ""),
            ])))])
        : h("span", { class: "dim" }, "no sampled spans yet"),
    ]));
    const evs = d.block_events || [];
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Recent block events"),
        h("span", { class: "sub" },
          "sampled denial records (obs/eventlog.py)")]),
      evs.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            ["time", "resource", "origin", "reason", "count"].map(t =>
              h("th", {}, t)))),
            h("tbody", {}, evs.map(e => h("tr", {}, [
              h("td", {}, new Date(e.ms).toTimeString().slice(0, 8)),
              h("td", {}, e.resource),
              h("td", {}, e.origin || "—"),
              h("td", {}, e.reason_name || String(e.reason)),
              h("td", { class: "num" }, String(e.count)),
            ])))])
        : h("span", { class: "dim" }, "no sampled block events yet"),
    ]));
  }
  await refresh();
  setRefresh(refresh, 5000);
}

// ------------------------------------------------------------------ hot keys
// Device-resident hot-resource telemetry (agent `topk` command →
// /obs/topk.json): sharded top-K by rolling pass+block QPS + the
// engine-wide per-second timeline ring (obs/telemetry.py).
async function viewHotKeys(c) {
  await loadMachines();
  const sel = machineSelector(() => refresh());
  const body = h("div", {});
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Hot Resources — ${S.app}`),
                 h("span", { class: "toolbar" }, [
                   h("span", { class: "sub" }, "machine"), sel])]),
    body,
  ]));
  async function refresh() {
    if (!S.machineSel) {
      body.innerHTML = "";
      body.appendChild(h("span", { class: "dim" }, "no healthy machine"));
      return;
    }
    const [ip, port] = S.machineSel.split(":");
    const j = await api(`/obs/topk.json?ip=${ip}&port=${port}&timeline=60`);
    body.innerHTML = "";
    if (!j || !j.success) {
      body.appendChild(h("span", { class: "bad" }, j ? j.msg : "error"));
      return;
    }
    const d = j.data || {};
    if (!d.enabled) {
      body.appendChild(h("span", { class: "dim" },
        "telemetry disabled on this agent (SENTINEL_TELEMETRY_DISABLE " +
        "or SENTINEL_OBS_DISABLE)"));
      return;
    }
    body.appendChild(h("span", { class: "sub" },
      `k=${d.k} · ${d.n_shards} shard(s) × ${d.rows_per_shard} rows · ` +
      `ticks ${d.ticks} · readback drops ${d.drops}`));
    const hot = d.hot || [];
    // per-resource RT quantile columns from the device-resident histogram
    // table — absent when SENTINEL_RESOURCE_HIST_DISABLE is set
    const hasHist = hot.some(r => r.rt_p99_ms !== undefined);
    const hotCols = ["resource", "row", "qps", "load", "pass", "block",
                     "success", "exception"]
      .concat(hasHist ? ["p50 ms", "p95 ms", "p99 ms"] : []);
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Top-K by rolling QPS"),
        h("span", { class: "sub" },
          "device-side lax.top_k merged across row shards (exact)" +
          (hasHist ? " · RT quantiles from the cumulative device histogram"
                   : ""))]),
      hot.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            hotCols.map(t => h("th", {}, t)))),
            h("tbody", {}, hot.map(r => h("tr", {}, [
              h("td", {}, r.resource),
              h("td", { class: "num" }, String(r.row)),
              h("td", { class: "num" }, String(r.qps)),
              h("td", { class: "num" }, String(r.load)),
              h("td", { class: "num" }, String(r.pass)),
              h("td", { class: "num" }, String(r.block)),
              h("td", { class: "num" }, String(r.success)),
              h("td", { class: "num" }, String(r.exception)),
            ].concat(hasHist ? [
              h("td", { class: "num" },
                r.rt_p50_ms !== undefined ? String(r.rt_p50_ms) : "—"),
              h("td", { class: "num" },
                r.rt_p95_ms !== undefined ? String(r.rt_p95_ms) : "—"),
              h("td", { class: "num" },
                r.rt_p99_ms !== undefined ? String(r.rt_p99_ms) : "—"),
            ] : []))))])
        : h("span", { class: "dim" }, "no hot resources yet"),
    ]));
    const tl = d.timeline || [];
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Per-second timeline"),
        h("span", { class: "sub" },
          "engine-wide aggregates from the device ring buffer " +
          "(newest last)")]),
      tl.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            ["time", "pass", "block", "success", "exception",
             "occupied", "rt sum (ms)"].map(t => h("th", {}, t)))),
            h("tbody", {}, tl.slice(-30).map(e => h("tr", {}, [
              h("td", {}, new Date(e.sec * 1000).toTimeString().slice(0, 8)),
              h("td", { class: "num" }, String(e.pass)),
              h("td", { class: "num" }, String(e.block)),
              h("td", { class: "num" }, String(e.success)),
              h("td", { class: "num" }, String(e.exception)),
              h("td", { class: "num" }, String(e.occupied_pass)),
              h("td", { class: "num" }, Number(e.rt_sum).toFixed(1)),
            ])))])
        : h("span", { class: "dim" }, "no timeline seconds yet"),
    ]));
  }
  await refresh();
  setRefresh(refresh, 5000);
}

// ------------------------------------------------------------------ control
// Overload-controller state + audit trail (agent `control` command →
// /obs/control.json): admission fraction, estimator extrema, degrade
// trackers, and the applied-action tail with evidence (control/loop.py).
async function viewControl(c) {
  await loadMachines();
  const sel = machineSelector(() => refresh());
  const body = h("div", {});
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Overload Control — ${S.app}`),
                 h("span", { class: "toolbar" }, [
                   h("span", { class: "sub" }, "machine"), sel])]),
    body,
  ]));
  async function refresh() {
    if (!S.machineSel) {
      body.innerHTML = "";
      body.appendChild(h("span", { class: "dim" }, "no healthy machine"));
      return;
    }
    const [ip, port] = S.machineSel.split(":");
    const j = await api(`/obs/control.json?ip=${ip}&port=${port}&actions=32`);
    body.innerHTML = "";
    if (!j || !j.success) {
      body.appendChild(h("span", { class: "bad" },
        j ? j.msg + " (no controller attached on this agent?)" : "error"));
      return;
    }
    const d = j.data || {};
    if (!d.enabled) {
      body.appendChild(h("span", { class: "dim" },
        "controller disabled on this agent (SENTINEL_CONTROL_DISABLE)"));
      return;
    }
    const p = d.policy || {};
    const ob = d.last_obs;
    body.appendChild(h("span", { class: "sub" },
      `interval ${d.interval_ms}ms · ticks ${d.ticks} · ` +
      `actions ${d.total_actions} · admit ` +
      `${(100 * (p.admit_frac == null ? 1 : p.admit_frac)).toFixed(1)}%` +
      (p.degraded_batcher ? " · batcher retuned" : "")));
    if (ob) {
      body.appendChild(h("div", { class: "card" }, [
        h("h3", {}, [h("span", {}, "Last observation"),
          h("span", { class: "sub" },
            "interval p99 from the rolling request histogram; " +
            "rate/RT extrema are windowed estimates")]),
        h("table", {}, [h("thead", {}, h("tr", {},
            ["p99 (ms)", "rt avg (ms)", "pass/s", "block/s", "queue",
             "max rate", "min rt (ms)"].map(t => h("th", {}, t)))),
          h("tbody", {}, [h("tr", {}, [
            h("td", { class: "num" }, String(ob.p99_ms)),
            h("td", { class: "num" }, String(ob.rt_avg_ms)),
            h("td", { class: "num" }, String(ob.pass_per_s)),
            h("td", { class: "num" }, String(ob.block_per_s)),
            h("td", { class: "num" },
              `${ob.queue_depth}/${ob.queue_max || "∞"}`),
            h("td", { class: "num" },
              p.max_rate == null ? "–" : String(p.max_rate)),
            h("td", { class: "num" },
              p.min_rt_ms == null ? "–" : String(p.min_rt_ms)),
          ])])]),
      ]));
    }
    const acts = d.actions || [];
    body.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, "Applied actions (newest last)"),
        h("span", { class: "sub" },
          "each one is also pinned in the flight recorder " +
          "(trigger kind controller_action)")]),
      acts.length
        ? h("table", {}, [h("thead", {}, h("tr", {},
            ["time", "action", "detail", "p99 (ms)", "queue"]
              .map(t => h("th", {}, t)))),
            h("tbody", {}, acts.map(a => h("tr", {}, [
              h("td", {},
                new Date(a.ts_ms).toTimeString().slice(0, 8)),
              h("td", {}, a.kind),
              h("td", {}, a.note),
              h("td", { class: "num" },
                String((a.evidence || {}).p99_ms)),
              h("td", { class: "num" },
                String((a.evidence || {}).queue_depth)),
            ])))])
        : h("span", { class: "dim" },
            "no interventions yet — the loop is holding"),
    ]));
  }
  await refresh();
  setRefresh(refresh, 5000);
}

// ------------------------------------------------------------------ resources
// shared by the resource + tree views: healthy-machine <select> wired to
// S.machineSel (call after loadMachines())
function machineSelector(refresh) {
  const healthy = S.machines.filter(m => m.healthy);
  if (!S.machineSel || !healthy.some(m => `${m.ip}:${m.port}` === S.machineSel)) {
    S.machineSel = healthy.length ? `${healthy[0].ip}:${healthy[0].port}` : "";
  }
  return h("select", { onchange: (e) => { S.machineSel = e.target.value; refresh(); } },
    healthy.map(m => {
      const v = `${m.ip}:${m.port}`;
      return h("option", v === S.machineSel ? { value: v, selected: "" }
                                            : { value: v }, v);
    }));
}

// shared per-origin drill-down subtable row (agent `origin` command)
async function originsSubtable(ip, port, resource, colspan) {
  const o = await api(`/resource/origin.json?ip=${ip}&port=${port}&id=${encodeURIComponent(resource)}`);
  const origins = (o && o.data) || [];
  return h("tr", {}, h("td", { colspan },
    origins.length
      ? h("table", {}, [
          h("thead", {}, h("tr", {}, ["origin", "pass", "block",
            "success", "exception", "threads"].map(t => h("th", {}, t)))),
          h("tbody", {}, origins.map(g => h("tr", {}, [
            h("td", {}, g.origin),
            h("td", { class: "num ok" }, String(g.passQps)),
            h("td", { class: "num" }, String(g.blockQps)),
            h("td", { class: "num" }, String(g.successQps)),
            h("td", { class: "num" }, String(g.exceptionQps)),
            h("td", { class: "num" }, String(g.threadNum)),
          ])))])
      : h("span", { class: "dim" },
          "no per-origin traffic on this resource")));
}

async function viewResources(c) {
  await loadMachines();
  const sel = machineSelector(() => refresh());
  const tbody = h("tbody", {});
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Resource View — ${S.app}`),
                 h("span", { class: "toolbar" }, [
                   h("span", { class: "sub" }, "machine"), sel])]),
    h("table", {}, [h("thead", {}, h("tr", {}, [
      ["resource", ""], ["pass", "num"], ["block", "num"], ["total", "num"],
      ["success", "num"], ["exception", "num"], ["rt ms", "num"],
      ["threads", "num"], ["", ""],
    ].map(([t, cl]) => h("th", { class: cl }, t)))), tbody]),
  ]));
  async function refresh() {
    if (!S.machineSel) { tbody.innerHTML = ""; tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "dim" }, "no healthy machine"))); return; }
    const [ip, port] = S.machineSel.split(":");
    const j = await api(`/resource/machineResource.json?ip=${ip}&port=${port}`);
    tbody.innerHTML = "";
    if (!j || !j.success) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "bad" },
        j ? j.msg : "error")));
      return;
    }
    for (const n of (j.data || [])) {
      const row = h("tr", {}, [
        h("td", {}, n.resource),
        h("td", { class: "num ok" }, String(n.passQps)),
        h("td", { class: "num " + (n.blockQps ? "bad" : "") }, String(n.blockQps)),
        h("td", { class: "num" }, String(n.totalQps)),
        h("td", { class: "num" }, String(n.successQps)),
        h("td", { class: "num " + (n.exceptionQps ? "warn" : "") }, String(n.exceptionQps)),
        h("td", { class: "num" }, String(n.averageRt)),
        h("td", { class: "num" }, String(n.threadNum)),
        h("td", {}, [
          h("button", { class: "sm", onclick: () => {
            // per-origin drill-down (agent `origin` command); state
            // survives the 3 s auto-refresh rebuild
            if (S.openOrigins.has(n.resource)) S.openOrigins.delete(n.resource);
            else S.openOrigins.add(n.resource);
            refresh();
          } }, "origins"),
          " ",
          h("button", { class: "sm",
            onclick: () => openRuleModal("flow", { resource: n.resource }) },
            "+ flow rule"),
        ]),
      ]);
      tbody.appendChild(row);
      if (S.openOrigins.has(n.resource)) {
        tbody.appendChild(await originsSubtable(ip, port, n.resource, 9));
      }
    }
    if (!(j.data || []).length) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "dim" },
        "no live resources on this machine")));
    }
  }
  await refresh();
  setRefresh(refresh, 3000);
}

// ------------------------------------------------------------------ tree
// The reference webapp's identity/resource-tree page (identity.js): the
// machine's invocation tree — EntranceNode root (__total_inbound_traffic__,
// the ENTRY row aggregate) with its resource DefaultNodes indented under
// it, per-origin drill-down per node, and rule creation from a row.
async function viewTree(c) {
  await loadMachines();
  const sel = machineSelector(() => refresh());
  const tbody = h("tbody", {});
  let apiNamesCache = null;   // per-view-load cache of API-group names
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Node Tree — ${S.app}`),
                 h("span", { class: "toolbar" }, [
                   h("span", { class: "sub" }, "machine"), sel])]),
    h("table", {}, [h("thead", {}, h("tr", {}, [
      ["resource", ""], ["threads", "num"], ["total", "num"],
      ["pass", "num"], ["block", "num"], ["success", "num"],
      ["exception", "num"], ["rt ms", "num"], ["", ""],
    ].map(([t, cl]) => h("th", { class: cl }, t)))), tbody]),
  ]));
  async function refresh() {
    if (!S.machineSel) { tbody.innerHTML = ""; tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "dim" }, "no healthy machine"))); return; }
    const [ip, port] = S.machineSel.split(":");
    const j = await api(`/resource/jsonTree.json?ip=${ip}&port=${port}`);
    tbody.innerHTML = "";
    if (!j || !j.success) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "bad" },
        j ? j.msg : "error")));
      return;
    }
    const nodes = j.data || [];
    const root = nodes.find(n => n.resource === "__total_inbound_traffic__");
    // gateway-classified resources (ResourceTypeConstants gateway = 3) get
    // their own section, the reference gateway identity page's tree
    const children = nodes.filter(n => n !== root && n.classification !== 3);
    const gateway = nodes.filter(n => n !== root && n.classification === 3);
    const rootCells = root
      ? [String(root.threadNum), String(root.totalQps), String(root.passQps),
         String(root.blockQps), String(root.successQps),
         String(root.exceptionQps), String(root.averageRt)]
      : ["0", "0", "0", "0", "0", "0", "0"];
    tbody.appendChild(h("tr", {}, [
      h("td", {}, h("b", {}, "machine-root (total inbound)")),
      ...rootCells.map((v, i) => h("td", { class: "num" + (i === 3 && v !== "0" ? " bad" : "") }, v)),
      h("td", {}),
    ]));
    for (const n of children) {
      tbody.appendChild(h("tr", {}, [
        h("td", {}, `  └─ ${n.resource}`),
        h("td", { class: "num" }, String(n.threadNum)),
        h("td", { class: "num" }, String(n.totalQps)),
        h("td", { class: "num ok" }, String(n.passQps)),
        h("td", { class: "num " + (n.blockQps ? "bad" : "") }, String(n.blockQps)),
        h("td", { class: "num" }, String(n.successQps)),
        h("td", { class: "num " + (n.exceptionQps ? "warn" : "") }, String(n.exceptionQps)),
        h("td", { class: "num" }, String(n.averageRt)),
        h("td", {}, [
          h("button", { class: "sm", onclick: () => {
            if (S.openOrigins.has(n.resource)) S.openOrigins.delete(n.resource);
            else S.openOrigins.add(n.resource);
            refresh();
          } }, "origins"),
          " ",
          h("button", { class: "sm",
            onclick: () => openRuleModal("flow", { resource: n.resource }) },
            "+ flow rule"),
          " ",
          h("button", { class: "sm",
            onclick: () => openRuleModal("degrade", { resource: n.resource }) },
            "+ degrade rule"),
        ]),
      ]));
      if (S.openOrigins.has(n.resource)) {
        tbody.appendChild(await originsSubtable(ip, port, n.resource, 9));
      }
    }
    if (gateway.length) {
      // which gateway resources are API groups (vs routes) comes from the
      // app's API definitions, same as the reference gateway identity
      // page — fetched once per view load (each fetch round-trips to the
      // agent), not on every 3 s tree poll
      if (apiNamesCache === null) {
        const aj = await api(`/v1/gatewayApi/rules?app=${encodeURIComponent(S.app)}`);
        apiNamesCache = new Set(((aj && aj.data) || []).map(r => r.apiName));
      }
      const apiNames = apiNamesCache;
      tbody.appendChild(h("tr", {}, [
        h("td", { colspan: 9 },
          h("b", {}, "gateway — routes and API groups"))]));
      for (const n of gateway) {
        const kind = apiNames.has(n.resource) ? "API group" : "route";
        tbody.appendChild(h("tr", {}, [
          h("td", {}, [`  └─ ${n.resource} `,
                       h("span", { class: "sub" }, `[${kind}]`)]),
          h("td", { class: "num" }, String(n.threadNum)),
          h("td", { class: "num" }, String(n.totalQps)),
          h("td", { class: "num ok" }, String(n.passQps)),
          h("td", { class: "num " + (n.blockQps ? "bad" : "") },
            String(n.blockQps)),
          h("td", { class: "num" }, String(n.successQps)),
          h("td", { class: "num " + (n.exceptionQps ? "warn" : "") },
            String(n.exceptionQps)),
          h("td", { class: "num" }, String(n.averageRt)),
          h("td", {}, h("button", { class: "sm",
            onclick: () => openRuleModal("gatewayFlow",
                                         { resource: n.resource }) },
            "+ gateway rule")),
        ]));
      }
    }
    if (!children.length && !gateway.length && !root) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 9, class: "dim" },
        "no live nodes on this machine")));
    }
  }
  await refresh();
  setRefresh(refresh, 3000);
}

// ------------------------------------------------------------------ cluster
const MODES = { "-1": "off", 0: "client", 1: "server" };
async function viewCluster(c) {
  const tbody = h("tbody", {});
  const topo = h("div", {});
  const srvConfig = h("div", {});
  const srvMonitor = h("div", {});
  const srvMetrics = h("div", {});
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `Cluster — ${S.app}`)]), topo]));
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, "Machines"),
      h("span", { class: "sub" },
        "assign = make that machine the token server, bind the rest as clients")]),
    h("table", {}, [h("thead", {}, h("tr", {}, [
      "machine", "mode", "token server", "",
    ].map(t => h("th", {}, t)))), tbody]),
  ]));
  c.appendChild(srvConfig);
  c.appendChild(srvMonitor);
  c.appendChild(srvMetrics);

  // --- token-server config editor (reference cluster_app_server_manage) —
  // rebuilt only when the server machine changes so edits aren't clobbered
  let cfgKey = null;
  async function refreshServerConfig(server) {
    const key = server ? `${server.ip}:${server.port}` : "";
    if (key === cfgKey) return;
    cfgKey = key;
    srvConfig.innerHTML = "";
    if (!server) return;
    let j;
    try {
      j = await api(`/cluster/serverConfig.json?ip=${server.ip}&port=${server.port}`);
    } catch (e) {
      cfgKey = null;        // transient fetch failure: retry next poll
      return;
    }
    if (!j || !j.success) { cfgKey = null; return; }
    const cfg = j.data || {};
    const nsList = (cfg.namespaceSet && cfg.namespaceSet.length)
      ? cfg.namespaceSet : [S.app];
    const nsInput = h("input", { value: nsList.join(", "), size: "40" });
    const nsSel = h("select", {},
      nsList.map(ns => h("option", { value: ns }, ns)));
    const qpsInput = h("input", { type: "number", min: "0",
                                  placeholder: "unlimited" });
    const applied = h("span", { class: "sub" }, "");
    const loadQps = async () => {
      let r = null;
      try {
        r = await api(`/cluster/serverConfig.json?ip=${server.ip}&port=${server.port}&namespace=${encodeURIComponent(nsSel.value)}`);
      } catch (e) { /* transient: leave the field; onchange retries */ }
      const v = (r && r.success && r.data && r.data.flow)
        ? r.data.flow.maxAllowedQps : null;
      qpsInput.value = (v == null || v < 0) ? "" : String(v);
      applied.textContent = "";
    };
    nsSel.onchange = loadQps;
    await loadQps();
    const sub = (cfg.transport
      ? `token port :${cfg.transport.port} · idle ${cfg.transport.idleSeconds}s · `
      : "") + (cfg.flow
      ? `window ${cfg.flow.intervalMs}ms × ${cfg.flow.sampleCount} buckets`
      : "");
    srvConfig.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, `Token server config — ${key}`),
                   h("span", { class: "sub" }, sub)]),
      h("div", { class: "toolbar" }, [
        h("span", { class: "sub" }, "namespace set"), nsInput,
        h("button", { class: "sm primary", onclick: async () => {
          const r = await post("/cluster/serverConfig",
            { ip: server.ip, port: server.port, namespaces: nsInput.value });
          if (r && !r.success) alert(r.msg);
          cfgKey = null; refreshServerConfig(server);
        } }, "save set"),
      ]),
      h("div", { class: "toolbar" }, [
        h("span", { class: "sub" }, "maxAllowedQps"), nsSel, qpsInput,
        h("button", { class: "sm primary", onclick: async () => {
          if (qpsInput.value === "") { alert("enter a QPS ceiling"); return; }
          const r = await post("/cluster/serverConfig",
            { ip: server.ip, port: server.port, namespace: nsSel.value,
              maxAllowedQps: qpsInput.value });
          if (r && !r.success) alert(r.msg);
          else applied.textContent = "applied";
        } }, "apply"),
        applied,
      ]),
    ]));
  }

  // --- token-server QPS monitor (reference cluster_app_server_monitor) —
  // granted/rejected per poll, charted from a client-side history
  let monKey = null, monCv = null;
  function ensureMonitor(server) {
    const key = server ? `${server.ip}:${server.port}` : "";
    if (key === monKey) return;
    monKey = key;
    srvMonitor.innerHTML = "";
    monCv = null;
    if (!server) return;
    monCv = h("canvas", { class: "chart" });
    srvMonitor.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, `Token server QPS — ${key}`),
        h("span", { class: "sub" },
          "granted (pass) vs rejected (block), summed over cluster flows")]),
      monCv]));
  }

  async function refreshServerMetrics(server) {
    refreshServerConfig(server);
    ensureMonitor(server);
    srvMetrics.innerHTML = "";
    if (!server) return;
    const j = await api(`/cluster/metrics.json?app=${encodeURIComponent(S.app)}&ip=${server.ip}&port=${server.port}`);
    if (!j || !j.success) return;
    const flows = j.data || [];
    const hist = (S.clusterHist = S.clusterHist || {});
    const pts = (hist[monKey] = hist[monKey] || []);
    pts.push({
      timestamp: Date.now(),
      passQps: flows.reduce((a, n) => a + (+n.passQps || 0), 0),
      blockQps: flows.reduce((a, n) => a + (+n.blockQps || 0), 0),
      rt: 0,
    });
    if (pts.length > 180) pts.shift();
    if (monCv) drawChart(monCv, pts, null);
    const rows = flows.map(n => h("tr", {}, [
      h("td", {}, String(n.flowId)),
      h("td", {}, n.resourceName),
      h("td", { class: "num ok" }, String(n.passQps)),
      h("td", { class: "num " + (n.blockQps ? "bad" : "") },
        String(n.blockQps)),
    ]));
    srvMetrics.appendChild(h("div", { class: "card" }, [
      h("h3", {}, [h("span", {}, `Token server flows — ${server.ip}:${server.port}`),
        h("span", { class: "sub" }, "current-window pass/block per cluster flow")]),
      h("table", {}, [h("thead", {}, h("tr", {},
        ["flow id", "resource", "pass", "block"].map(t => h("th", {}, t)))),
        h("tbody", {}, rows.length ? rows
          : h("tr", {}, h("td", { colspan: 4, class: "dim" },
              "no cluster rules loaded on the token server")))]),
    ]));
  }
  async function refresh() {
    const j = await api(`/cluster/state.json?app=${encodeURIComponent(S.app)}`);
    if (!j) return;
    const states = j.data || [];
    tbody.innerHTML = "";
    for (const s of states) {
      const srv = s.serverPort
        ? `listening :${s.serverPort}` +
          (s.connectedCount != null ? ` · ${s.connectedCount} clients` : "")
        : (s.serverHost ? `→ ${s.serverHost}:${s.clientServerPort ?? s.serverPort ?? ""}` : "—");
      const modeSel = h("select", {},
        Object.entries(MODES).map(([v, l]) =>
          h("option", String(s.mode) === String(v)
            ? { value: v, selected: "" } : { value: v }, l)));
      tbody.appendChild(h("tr", {}, [
        h("td", {}, `${s.ip}:${s.port}`),
        h("td", {}, [modeSel, " ", h("button", { class: "sm", onclick: async () => {
          await post("/cluster/mode", { app: S.app, ip: s.ip, port: s.port,
                                        mode: +modeSel.value });
          refresh();
        } }, "apply")]),
        h("td", {}, srv),
        h("td", {}, h("button", { class: "sm primary", onclick: async () => {
          const r = await post("/cluster/assign",
            { app: S.app, serverIp: s.ip, serverPort: s.port });
          if (r && !r.success) alert(r.msg);
          refresh();
        } }, "assign")),
      ]));
    }
    if (!states.length) {
      tbody.appendChild(h("tr", {}, h("td", { colspan: 4, class: "dim" },
        "no machines")));
    }
    drawTopology(topo, states);
    refreshServerMetrics(states.find(s => s.mode === 1));
  }
  await refresh();
  setRefresh(refresh, 10000);
}

function drawTopology(container, states) {
  container.innerHTML = "";
  if (!states.length) {
    container.appendChild(h("div", { class: "empty" }, "no machines"));
    return;
  }
  const server = states.find(s => s.mode === 1);
  const others = states.filter(s => s !== server);
  const W = 700, H = 240, ns = "http://www.w3.org/2000/svg";
  const svg = document.createElementNS(ns, "svg");
  svg.setAttribute("class", "topo");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  const node = (x, y, label, cls) => {
    const g = document.createElementNS(ns, "g");
    g.setAttribute("class", cls);
    const rect = document.createElementNS(ns, "rect");
    rect.setAttribute("x", x - 75); rect.setAttribute("y", y - 18);
    rect.setAttribute("width", 150); rect.setAttribute("height", 36);
    rect.setAttribute("rx", 8);
    const text = document.createElementNS(ns, "text");
    text.setAttribute("x", x); text.setAttribute("y", y + 4);
    text.setAttribute("text-anchor", "middle");
    text.textContent = label;
    g.appendChild(rect); g.appendChild(text);
    svg.appendChild(g);
  };
  const edge = (x1, y1, x2, y2) => {
    const l = document.createElementNS(ns, "line");
    l.setAttribute("x1", x1); l.setAttribute("y1", y1);
    l.setAttribute("x2", x2); l.setAttribute("y2", y2);
    svg.appendChild(l);
  };
  const sx = W / 2, sy = 40;
  const n = others.length, step = W / Math.max(1, n);
  others.forEach((s, i) => {
    const cx = step * (i + 0.5), cy = H - 50;
    if (server) edge(sx, sy + 18, cx, cy - 18);
    node(cx, cy, `${s.ip}:${s.port} (${MODES[String(s.mode)] ?? s.mode})`,
         s.mode === 0 ? "cli" : "cli off");
  });
  if (server) {
    node(sx, sy, `token server ${server.ip}:${server.port}` +
         (server.serverPort ? ` :${server.serverPort}` : ""), "srv");
  } else {
    const t = document.createElementNS(ns, "text");
    t.setAttribute("x", W / 2); t.setAttribute("y", 26);
    t.setAttribute("text-anchor", "middle");
    t.setAttribute("fill", "#7f8ea0");
    t.textContent = "no token server assigned";
    svg.appendChild(t);
  }
  container.appendChild(svg);
}

// ------------------------------------------------------------------ rules
const E = {   // enum label maps (reference RuleConstant / gateway constants)
  flowGrade: { 0: "Thread", 1: "QPS" },
  strategy: { 0: "Direct", 1: "Relate", 2: "Chain" },
  behavior: { 0: "Reject", 1: "Warm Up", 2: "Rate Limiter",
              3: "Warm Up + Rate Limiter" },
  degradeGrade: { 0: "Slow ratio (RT)", 1: "Exception ratio",
                  2: "Exception count" },
  authStrategy: { 0: "Whitelist", 1: "Blacklist" },
  resourceMode: { 0: "Route ID", 1: "API Group" },
  parseStrategy: { 0: "Client IP", 1: "Host", 2: "Header", 3: "URL Param",
                   4: "Cookie" },
  paramMatch: { 0: "Exact", 1: "Prefix", 2: "Regex", 3: "Contains" },
  urlMatch: { 0: "Exact", 1: "Prefix", 2: "Regex" },
  thresholdType: { 0: "Avg Local", 1: "Global" },
};

// field spec: n(ame/path) l(abel) k(ind: text num sel chk json) o(ptions)
// d(efault) req show(fn of current values)
const SCHEMAS = {
  flow: [
    { n: "resource", l: "Resource", k: "text", req: true },
    { n: "limitApp", l: "Limit origin (limitApp)", k: "text", d: "default" },
    { n: "grade", l: "Grade", k: "sel", o: E.flowGrade, d: 1 },
    { n: "count", l: "Threshold", k: "num", d: 10 },
    { n: "strategy", l: "Strategy", k: "sel", o: E.strategy, d: 0 },
    { n: "refResource", l: "Ref resource / entrance", k: "text", d: "",
      show: v => +v.strategy !== 0 },
    { n: "controlBehavior", l: "Control behavior", k: "sel", o: E.behavior,
      d: 0 },
    { n: "warmUpPeriodSec", l: "Warm-up period (s)", k: "num", d: 10,
      show: v => +v.controlBehavior === 1 || +v.controlBehavior === 3 },
    { n: "maxQueueingTimeMs", l: "Max queueing time (ms)", k: "num", d: 500,
      show: v => +v.controlBehavior === 2 || +v.controlBehavior === 3 },
    { n: "clusterMode", l: "Cluster mode", k: "chk", d: false },
    { n: "clusterConfig.flowId", l: "Cluster flow ID", k: "num", d: 0,
      show: v => v.clusterMode },
    { n: "clusterConfig.thresholdType", l: "Threshold type", k: "sel",
      o: E.thresholdType, d: 0, show: v => v.clusterMode },
    { n: "clusterConfig.fallbackToLocalWhenFail", l: "Fallback to local",
      k: "chk", d: true, show: v => v.clusterMode },
  ],
  degrade: [
    { n: "resource", l: "Resource", k: "text", req: true },
    { n: "grade", l: "Strategy", k: "sel", o: E.degradeGrade, d: 0 },
    { n: "count", l: "Threshold (max RT ms / ratio / count)", k: "num",
      d: 0.5 },
    { n: "slowRatioThreshold", l: "Slow ratio threshold", k: "num", d: 1.0,
      show: v => +v.grade === 0 },
    { n: "timeWindow", l: "Recovery window (s)", k: "num", d: 10 },
    { n: "minRequestAmount", l: "Min request amount", k: "num", d: 5 },
    { n: "statIntervalMs", l: "Stat interval (ms)", k: "num", d: 1000 },
  ],
  paramFlow: [
    { n: "resource", l: "Resource", k: "text", req: true },
    { n: "paramIdx", l: "Param index", k: "num", d: 0 },
    { n: "grade", l: "Grade", k: "sel", o: E.flowGrade, d: 1 },
    { n: "count", l: "Threshold", k: "num", d: 10 },
    { n: "durationInSec", l: "Duration (s)", k: "num", d: 1 },
    { n: "burstCount", l: "Burst", k: "num", d: 0 },
    { n: "controlBehavior", l: "Control behavior", k: "sel",
      o: { 0: "Reject", 2: "Rate Limiter" }, d: 0 },
    { n: "maxQueueingTimeMs", l: "Max queueing time (ms)", k: "num", d: 0,
      show: v => +v.controlBehavior === 2 },
    { n: "paramFlowItemList", l: "Per-item overrides (JSON)", k: "json",
      d: [], hint: '[{"object":"vip","count":100,"classType":"String"}]' },
    { n: "clusterMode", l: "Cluster mode", k: "chk", d: false },
    { n: "clusterConfig.flowId", l: "Cluster flow ID", k: "num", d: 0,
      show: v => v.clusterMode },
  ],
  system: [
    { n: "highestSystemLoad", l: "Max load1 (-1 = off)", k: "num", d: -1 },
    { n: "highestCpuUsage", l: "Max CPU usage 0..1 (-1 = off)", k: "num",
      d: -1 },
    { n: "qps", l: "Max total QPS (-1 = off)", k: "num", d: -1 },
    { n: "avgRt", l: "Max avg RT ms (-1 = off)", k: "num", d: -1 },
    { n: "maxThread", l: "Max threads (-1 = off)", k: "num", d: -1 },
  ],
  authority: [
    { n: "resource", l: "Resource", k: "text", req: true },
    { n: "limitApp", l: "Origins (comma-separated)", k: "text", req: true },
    { n: "strategy", l: "Mode", k: "sel", o: E.authStrategy, d: 0 },
  ],
  gatewayFlow: [
    { n: "resource", l: "Route ID / API group", k: "text", req: true },
    { n: "resourceMode", l: "Resource mode", k: "sel", o: E.resourceMode,
      d: 0 },
    { n: "grade", l: "Grade", k: "sel", o: E.flowGrade, d: 1 },
    { n: "count", l: "Threshold", k: "num", d: 10 },
    { n: "intervalSec", l: "Interval (s)", k: "num", d: 1 },
    { n: "controlBehavior", l: "Control behavior", k: "sel",
      o: { 0: "Reject", 2: "Rate Limiter" }, d: 0 },
    { n: "burst", l: "Burst", k: "num", d: 0 },
    { n: "maxQueueingTimeoutMs", l: "Max queueing timeout (ms)", k: "num",
      d: 500, show: v => +v.controlBehavior === 2 },
    { n: "_hasParam", l: "Limit by request attribute", k: "chk", d: false,
      virtual: true },
    { n: "paramItem.parseStrategy", l: "Attribute", k: "sel",
      o: E.parseStrategy, d: 0, show: v => v._hasParam },
    { n: "paramItem.fieldName", l: "Field name (header/param/cookie)",
      k: "text", d: "", show: v => v._hasParam && +getPath(v, "paramItem.parseStrategy") >= 2 },
    { n: "paramItem.pattern", l: "Match pattern (optional)", k: "text", d: "",
      show: v => v._hasParam },
    { n: "paramItem.matchStrategy", l: "Match strategy", k: "sel",
      o: E.paramMatch, d: 0, show: v => v._hasParam && !!getPath(v, "paramItem.pattern") },
  ],
  gatewayApi: [
    { n: "apiName", l: "API group name", k: "text", req: true },
    { n: "predicateItems", l: "Path predicates (JSON)", k: "json",
      d: [{ pattern: "/", matchStrategy: 1 }],
      hint: '[{"pattern":"/foo/**","matchStrategy":1}] — 0 exact, 1 prefix, 2 regex' },
  ],
};

// columns shown in each rule table: [header, render(rule)]
const COLS = {
  flow: [
    ["resource", r => r.resource],
    ["origin", r => r.limitApp],
    ["grade", r => E.flowGrade[r.grade] ?? r.grade],
    ["threshold", r => r.count],
    ["strategy", r => E.strategy[r.strategy] ?? r.strategy],
    ["behavior", r => E.behavior[r.controlBehavior] ?? r.controlBehavior],
    ["cluster", r => r.clusterMode ? `yes (#${r.clusterConfig?.flowId ?? 0})` : "no"],
  ],
  degrade: [
    ["resource", r => r.resource],
    ["strategy", r => E.degradeGrade[r.grade] ?? r.grade],
    ["threshold", r => r.count],
    ["recovery (s)", r => r.timeWindow],
    ["min requests", r => r.minRequestAmount],
    ["stat interval", r => `${r.statIntervalMs} ms`],
  ],
  paramFlow: [
    ["resource", r => r.resource],
    ["param idx", r => r.paramIdx],
    ["grade", r => E.flowGrade[r.grade] ?? r.grade],
    ["threshold", r => r.count],
    ["duration (s)", r => r.durationInSec],
    ["items", r => (r.paramFlowItemList || []).length],
    ["cluster", r => r.clusterMode ? "yes" : "no"],
  ],
  system: [
    ["load1", r => r.highestSystemLoad],
    ["cpu", r => r.highestCpuUsage],
    ["qps", r => r.qps],
    ["avg rt", r => r.avgRt],
    ["threads", r => r.maxThread],
  ],
  authority: [
    ["resource", r => r.resource],
    ["origins", r => r.limitApp],
    ["mode", r => E.authStrategy[r.strategy] ?? r.strategy],
  ],
  gatewayFlow: [
    ["resource", r => r.resource],
    ["mode", r => E.resourceMode[r.resourceMode] ?? r.resourceMode],
    ["grade", r => E.flowGrade[r.grade] ?? r.grade],
    ["threshold", r => `${r.count} / ${r.intervalSec}s`],
    ["behavior", r => ({ 0: "Reject", 2: "Rate Limiter" })[r.controlBehavior] ?? r.controlBehavior],
    ["param", r => r.paramItem
      ? (E.parseStrategy[r.paramItem.parseStrategy] ?? "?") +
        (r.paramItem.fieldName ? `:${r.paramItem.fieldName}` : "")
      : "—"],
  ],
  gatewayApi: [
    ["api group", r => r.apiName],
    ["predicates", r => (r.predicateItems || [])
      .map(p => `${E.urlMatch[p.matchStrategy] ?? "?"} ${p.pattern}`)
      .join(", ")],
  ],
};

async function viewRules(c, rtype) {
  const tbody = h("tbody", {});
  const errBox = h("div", { class: "err" });
  const cols = COLS[rtype];
  c.appendChild(h("div", { class: "card" }, [
    h("h3", {}, [h("span", {}, `${VIEW_TITLES[rtype]} — ${S.app}`),
      h("span", { class: "toolbar" }, [
        h("button", { class: "sm", onclick: () => refreshRules(true) },
          "reload from machines"),
        h("button", { class: "sm primary",
          onclick: () => openRuleModal(rtype) }, "+ new"),
      ])]),
    errBox,
    h("table", {}, [h("thead", {}, h("tr", {},
      [...cols.map(([t]) => h("th", {}, t)), h("th", {}, "")])), tbody]),
  ]));
  async function refreshRules() {
    const j = await api(`/v1/${rtype}/rules?app=${encodeURIComponent(S.app)}`);
    tbody.innerHTML = "";
    errBox.textContent = (j && !j.success) ? j.msg : "";
    const rules = (j && j.data) || [];
    for (const r of rules) {
      tbody.appendChild(h("tr", {}, [
        ...cols.map(([, f]) => h("td", {}, String(f(r) ?? ""))),
        h("td", {}, [
          h("button", { class: "sm",
            onclick: () => openRuleModal(rtype, r) }, "edit"),
          " ",
          h("button", { class: "sm danger", onclick: async () => {
            if (!confirm("Delete this rule?")) return;
            const d = await api(`/v1/${rtype}/rule/${r.id}`,
                                { method: "DELETE" });
            if (d && !d.success) alert(d.msg);
            refreshRules();
          } }, "delete"),
        ]),
      ]));
    }
    if (!rules.length) {
      tbody.appendChild(h("tr", {}, h("td", {
        colspan: cols.length + 1, class: "dim" }, "no rules")));
    }
  }
  S.refreshRules = refreshRules;
  await refreshRules();
}

// ------------------------------------------------------------------ modal
function closeModal() {
  const m = $("#modal-bg");
  if (m) m.remove();
}

function openRuleModal(rtype, rule) {
  closeModal();
  if (S.view !== rtype) nav(S.app, rtype);
  const editing = rule && rule.id;
  const spec = SCHEMAS[rtype];
  // working values: defaults <- existing rule
  const vals = {};
  for (const f of spec) {
    const existing = rule ? getPath(rule, f.n) : undefined;
    setPath(vals, f.n, existing !== undefined ? existing
      : (f.k === "json" ? JSON.stringify(f.d) : f.d));
  }
  if (rtype === "gatewayFlow") vals._hasParam = !!(rule && rule.paramItem);
  const err = h("div", { class: "err" });

  function buildFields(form) {
    form.innerHTML = "";
    for (const f of spec) {
      if (f.show && !f.show(vals)) continue;
      const cur = getPath(vals, f.n);
      let input;
      if (f.k === "sel") {
        input = h("select", { onchange: (e) => {
          setPath(vals, f.n, +e.target.value); buildFields(form); } },
          Object.entries(f.o).map(([v, l]) =>
            h("option", String(cur) === String(v)
              ? { value: v, selected: "" } : { value: v }, l)));
      } else if (f.k === "chk") {
        input = h("input", { type: "checkbox", onchange: (e) => {
          setPath(vals, f.n, e.target.checked); buildFields(form); } });
        input.checked = !!cur;
      } else if (f.k === "json") {
        input = h("textarea", { oninput: (e) => setPath(vals, f.n, e.target.value) });
        input.value = typeof cur === "string" ? cur : JSON.stringify(cur);
      } else {
        input = h("input", {
          type: f.k === "num" ? "number" : "text",
          oninput: (e) => setPath(vals, f.n, e.target.value) });
        input.value = cur ?? "";
        if (f.k === "num") input.step = "any";
        if (editing && f.n === "resource") input.disabled = true;
      }
      if (f.k === "chk") {
        form.appendChild(h("div", { class: "field chk" },
          [input, h("label", {}, f.l)]));
      } else {
        form.appendChild(h("div", { class: "field" }, [
          h("label", {}, f.l + (f.req ? " *" : "")),
          input,
          f.hint ? h("div", { class: "legend" }, f.hint) : null,
        ]));
      }
    }
  }

  function collect() {
    const body = {};
    for (const f of spec) {
      if (f.show && !f.show(vals)) continue;
      if (f.virtual) continue;
      let v = getPath(vals, f.n);
      if (f.k === "num") {
        // Number("") === 0, which would silently save a 0 threshold (i.e.
        // block all traffic) when a field is cleared — empty/whitespace is
        // always a validation error, never a silent default substitution.
        if (v == null || String(v).trim() === "") {
          throw new Error(`${f.l}: not a number`);
        }
        v = Number(v);
        if (Number.isNaN(v)) throw new Error(`${f.l}: not a number`);
      }
      if (f.k === "json" && typeof v === "string") {
        try { v = JSON.parse(v || "null"); }
        catch (e) { throw new Error(`${f.l}: invalid JSON`); }
      }
      if (f.req && (v === "" || v == null)) {
        throw new Error(`${f.l} is required`);
      }
      setPath(body, f.n, v);
    }
    // unchecking "limit by attribute" must clear a previously saved
    // paramItem (PUT merges fields, so absence alone wouldn't remove it)
    if (rtype === "gatewayFlow" && !vals._hasParam) body.paramItem = null;
    return body;
  }

  const form = h("div", {});
  buildFields(form);
  const bg = h("div", { id: "modal-bg", onclick: (e) => {
    if (e.target.id === "modal-bg") closeModal(); } }, [
    h("div", { id: "modal" }, [
      h("h3", {}, `${editing ? "Edit" : "New"} — ${VIEW_TITLES[rtype]}`),
      form, err,
      h("div", { class: "actions" }, [
        h("button", { onclick: closeModal }, "Cancel"),
        h("button", { class: "primary", onclick: async () => {
          let body;
          try { body = collect(); }
          catch (e) { err.textContent = e.message; return; }
          const j = editing
            ? await post(`/v1/${rtype}/rule/${rule.id}`, body, "PUT")
            : await post(`/v1/${rtype}/rule`, { app: S.app, ...body });
          if (j && !j.success) { err.textContent = j.msg; return; }
          closeModal();
          if (S.refreshRules) S.refreshRules();
        } }, editing ? "Save" : "Create"),
      ]),
    ]),
  ]);
  document.body.appendChild(bg);
}

// ------------------------------------------------------------------ init
(async () => {
  $("#login form").onsubmit = doLogin;
  $("#logout").onclick = doLogout;
  const j = await fetch("/auth/check").then(r => r.json());
  const logged = j.data && j.data.loggedIn;
  showLogin(!logged);
  if (logged) boot();
  setInterval(async () => {   // keep the app list fresh
    if ($("#app").style.display === "none") return;
    const r = await api("/app/names.json");
    if (r && JSON.stringify(r.data) !== JSON.stringify(S.apps)) {
      S.apps = r.data || [];
      if (!S.app && S.apps.length) { S.app = S.apps[0]; route(); }
      else renderSidebar();
    }
  }, 10000);
})();
