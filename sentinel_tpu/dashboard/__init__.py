"""Control-plane dashboard (reference ``sentinel-dashboard``, SURVEY §2.5).

A standalone web app that discovers agents via heartbeats
(``/registry/machine``), polls their ``/metric`` command every few seconds
into an in-memory 5-minute ring, and offers rule CRUD that writes through to
every machine of an app over the agent command plane — the same
heartbeat → discovery → fetch → aggregate → chart and controller →
``SentinelApiClient`` → ``setRules`` flows as the reference
(``MachineRegistryController.java:36-45``, ``MetricFetcher.java:72-183``,
``client/SentinelApiClient.java:397-593``), rebuilt on the Python stdlib
HTTP stack with a single-file JS UI instead of Spring Boot + AngularJS.
"""

from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.repository import (
    InMemoryMetricsRepository, MetricEntity, RuleEntity, RuleRepository,
)
from sentinel_tpu.dashboard.client import SentinelApiClient
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.server import Dashboard, DashboardServer

__all__ = [
    "AppManagement", "MachineInfo",
    "InMemoryMetricsRepository", "MetricEntity",
    "RuleEntity", "RuleRepository",
    "SentinelApiClient", "MetricFetcher", "Dashboard", "DashboardServer",
]
