"""HTTP client to agent command planes (reference
``sentinel-dashboard/.../client/SentinelApiClient.java:397-593``).

Every operation maps to one agent command (SURVEY §2.4): ``getRules`` /
``setRules`` per type, ``metric`` with a time range, ``clusterNode`` /
``jsonTree`` for live node views, ``getClusterMode`` / ``setClusterMode``,
``version`` and ``systemStatus``.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from sentinel_tpu.metrics.node import MetricNode

DEFAULT_TIMEOUT_S = 3.0

# gateway rule families speak their own commands (reference
# ``SentinelApiClient.fetchApis/modifyApis`` + ``GatewayFlowRuleController``)
_GATEWAY_GET = {"gatewayFlow": "gateway/getRules",
                "gatewayApi": "gateway/getApiDefinitions"}
_GATEWAY_SET = {"gatewayFlow": "gateway/updateRules",
                "gatewayApi": "gateway/updateApiDefinitions"}


class AgentUnreachable(Exception):
    pass


class SentinelApiClient:
    def __init__(self, timeout_s: float = DEFAULT_TIMEOUT_S):
        self.timeout_s = timeout_s

    # ------------------------------------------------------------- plumbing
    def _get(self, ip: str, port: int, command: str,
             params: Optional[Dict[str, str]] = None) -> str:
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        url = f"http://{ip}:{port}/{command}{qs}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                return r.read().decode("utf-8")
        except (urllib.error.URLError, OSError) as exc:
            raise AgentUnreachable(f"{url}: {exc}") from exc

    def _post(self, ip: str, port: int, command: str,
              params: Dict[str, str]) -> str:
        url = f"http://{ip}:{port}/{command}"
        data = urllib.parse.urlencode(params).encode("utf-8")
        try:
            with urllib.request.urlopen(url, data=data,
                                        timeout=self.timeout_s) as r:
                return r.read().decode("utf-8")
        except (urllib.error.URLError, OSError) as exc:
            raise AgentUnreachable(f"{url}: {exc}") from exc

    # ------------------------------------------------------------- commands
    def version(self, ip: str, port: int) -> str:
        return self._get(ip, port, "version").strip()

    def fetch_rules(self, ip: str, port: int,
                    rule_type: str) -> List[Dict[str, Any]]:
        if rule_type in _GATEWAY_GET:
            text = self._get(ip, port, _GATEWAY_GET[rule_type])
        else:
            text = self._get(ip, port, "getRules", {"type": rule_type})
        return json.loads(text or "[]")

    def set_rules(self, ip: str, port: int, rule_type: str,
                  rules: List[Dict[str, Any]]) -> bool:
        if rule_type in _GATEWAY_SET:
            resp = self._post(ip, port, _GATEWAY_SET[rule_type],
                              {"data": json.dumps(rules)})
        else:
            resp = self._post(ip, port, "setRules", {
                "type": rule_type, "data": json.dumps(rules)})
        return "success" in resp

    def fetch_metrics(self, ip: str, port: int, start_ms: int,
                      end_ms: int) -> List[MetricNode]:
        text = self._get(ip, port, "metric", {
            "startTime": str(start_ms), "endTime": str(end_ms)})
        nodes = []
        for line in text.splitlines():
            line = line.strip()
            if not line or line == "No metrics":
                continue
            try:
                # agents serve the thin line format (SendMetricCommandHandler
                # returns MetricNode.toThinString)
                nodes.append(MetricNode.from_thin_string(line))
            except (ValueError, IndexError):
                continue
        return nodes

    def fetch_cluster_nodes(self, ip: str, port: int) -> List[Dict[str, Any]]:
        return json.loads(self._get(ip, port, "clusterNode") or "[]")

    def fetch_json_tree(self, ip: str, port: int) -> List[Dict[str, Any]]:
        return json.loads(self._get(ip, port, "jsonTree") or "[]")

    def fetch_system_status(self, ip: str, port: int) -> Dict[str, Any]:
        return json.loads(self._get(ip, port, "systemStatus") or "{}")

    def fetch_obs(self, ip: str, port: int,
                  spans: int = 128, events: int = 64,
                  trace: str = "") -> Dict[str, Any]:
        """Runtime self-telemetry snapshot (``obs`` command): counters,
        latency histograms, recent spans/block events; optionally one
        trace's full span chain."""
        params = {"spans": str(spans), "events": str(events)}
        if trace:
            params["trace"] = trace
        return json.loads(self._get(ip, port, "obs", params) or "{}")

    def fetch_topk(self, ip: str, port: int,
                   timeline: int = 60, tick: bool = False) -> Dict[str, Any]:
        """Hot-resource telemetry snapshot (``topk`` command —
        obs/telemetry.py): current top-K by rolling pass+block QPS plus
        the per-second engine-wide timeline. ``tick=True`` forces one
        device tick + readback first (operator poke when the background
        ticker is off)."""
        params = {"timeline": str(timeline)}
        if tick:
            params["tick"] = "1"
        return json.loads(self._get(ip, port, "topk", params) or "{}")

    def fetch_control(self, ip: str, port: int,
                      actions: int = 32, tick: bool = False
                      ) -> Dict[str, Any]:
        """Overload-controller snapshot (``control`` command —
        control/loop.py): admission fraction, estimator extrema, degrade
        trackers, the last observation, and the applied-action tail.
        ``tick=True`` runs one observe/decide/apply cycle inline first."""
        params = {"actions": str(actions)}
        if tick:
            params["tick"] = "1"
        return json.loads(self._get(ip, port, "control", params) or "{}")

    def fetch_trace(self, ip: str, port: int,
                    trace_id: str = "") -> Dict[str, Any]:
        """Request-scoped trace export (``trace`` command): with an id, a
        Chrome-trace-event/Perfetto document of that causal chain; without,
        the flight recorder's pinned-record index."""
        params = {"id": trace_id} if trace_id else None
        return json.loads(self._get(ip, port, "trace", params) or "{}")

    def get_cluster_mode(self, ip: str, port: int) -> Dict[str, Any]:
        return json.loads(self._get(ip, port, "getClusterMode") or "{}")

    def set_cluster_mode(self, ip: str, port: int, mode: int) -> bool:
        resp = self._post(ip, port, "setClusterMode", {"mode": str(mode)})
        return "success" in resp

    def fetch_origin_stats(self, ip: str, port: int,
                           resource: str) -> List[Dict[str, Any]]:
        """Per-origin rolling stats of one resource (agent ``origin``
        command — ``FetchOriginCommandHandler``)."""
        return json.loads(self._get(ip, port, "origin",
                                    {"id": resource}) or "[]")

    def fetch_cluster_server_info(self, ip: str, port: int) -> Dict[str, Any]:
        """``cluster/server/info`` (FetchClusterServerInfoCommandHandler)."""
        return json.loads(self._get(ip, port, "cluster/server/info") or "{}")

    def fetch_cluster_server_metrics(self, ip: str, port: int,
                                     namespace: str) -> List[Dict[str, Any]]:
        """Token-server per-flow current-window metrics
        (``cluster/server/metricList`` — ClusterMetricNode shapes)."""
        return json.loads(self._get(ip, port, "cluster/server/metricList",
                                    {"namespace": namespace}) or "[]")

    def set_cluster_client_config(self, ip: str, port: int,
                                  server_host: str, server_port: int,
                                  request_timeout: int = 0) -> bool:
        cfg = {"serverHost": server_host, "serverPort": server_port}
        if request_timeout:
            cfg["requestTimeout"] = request_timeout
        resp = self._post(ip, port, "setClusterClientConfig",
                          {"data": json.dumps(cfg)})
        return "success" in resp

    def fetch_cluster_server_config(self, ip: str, port: int,
                                    namespace: str = "") -> Dict[str, Any]:
        """``cluster/server/fetchConfig`` — ``{flow, namespaceSet,
        transport}`` without a namespace, or the per-namespace
        ``ServerFlowConfig`` view (``maxAllowedQps``) with one (reference
        ``FetchClusterServerConfigHandler``)."""
        params = {"namespace": namespace} if namespace else None
        return json.loads(self._get(ip, port, "cluster/server/fetchConfig",
                                    params) or "{}")

    def set_cluster_server_flow_config(self, ip: str, port: int,
                                       namespace: str,
                                       max_allowed_qps: float) -> bool:
        """Per-namespace ``ServerFlowConfig.maxAllowedQps`` (reference
        ``ModifyClusterServerFlowConfigHandler`` →
        ``GlobalRequestLimiter``)."""
        resp = self._post(
            ip, port, "cluster/server/modifyFlowConfig",
            {"namespace": namespace,
             "data": json.dumps({"maxAllowedQps": max_allowed_qps})})
        return "success" in resp

    def set_cluster_server_namespace_set(self, ip: str, port: int,
                                         namespaces: List[str]) -> bool:
        """Replace the token server's served-namespace set (reference
        ``ModifyServerNamespaceSetHandler``)."""
        resp = self._post(ip, port, "cluster/server/modifyNamespaceSet",
                          {"data": json.dumps(list(namespaces))})
        return "success" in resp
