"""Dashboard web server: REST API + static UI (reference
``sentinel-dashboard`` Spring Boot controllers, SURVEY §2.5).

Routes (all JSON wrapped in the reference's ``Result`` envelope
``{"success": bool, "code": int, "msg": str, "data": ...}``):

- ``POST /registry/machine``            heartbeat receiver (``MachineRegistryController.java:36-45``)
- ``POST /auth/login`` / ``/auth/logout`` / ``GET /auth/check``
- ``GET  /app/names.json`` / ``GET /app/{app}/machines.json``
- ``GET  /metric/resources.json?app=``
- ``GET  /metric/queryByAppAndResource.json?app&identity&startTime&endTime``
- ``GET  /v1/{type}/rules?app``         pull live rules from a machine into the repo
- ``POST/PUT/DELETE /v1/{type}/rule[/{id}]``  CRUD; every change re-publishes the
  app's full rule set to every healthy machine (``FlowControllerV1.publishRules``)
- ``GET  /resource/machineResource.json?ip&port``  live clusterNode view
- ``GET  /cluster/state.json?app`` / ``POST /cluster/mode``
- ``GET  /``                            single-file JS UI

Rule types: flow, degrade, system, authority, paramFlow (agent command
``getRules``/``setRules`` type keys), plus gatewayFlow / gatewayApi
(``gateway/getRules|updateRules|getApiDefinitions|updateApiDefinitions``,
reference ``GatewayFlowRuleController`` / ``GatewayApiController``).
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from sentinel_tpu.dashboard.auth import AuthService
from sentinel_tpu.dashboard.client import AgentUnreachable, SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.fetcher import MetricFetcher
from sentinel_tpu.dashboard.rulepipeline import RulePipelineRegistry
from sentinel_tpu.dashboard.repository import (
    InMemoryMetricsRepository, MetricEntity, RuleEntity, RuleRepository,
)

RULE_TYPES = ("flow", "degrade", "system", "authority", "paramFlow",
              "gatewayFlow", "gatewayApi")

_STATIC_DIR = Path(__file__).parent / "static"


def _ok(data: Any = None) -> dict:
    return {"success": True, "code": 0, "msg": "", "data": data}


def _fail(msg: str, code: int = -1) -> dict:
    return {"success": False, "code": code, "msg": msg, "data": None}


class Dashboard:
    """Wires discovery + repos + fetcher + api client; host for route logic."""

    def __init__(self, *, username: str = "sentinel",
                 password: str = "sentinel", clock=None,
                 agent_timeout_s: Optional[float] = None):
        import os
        self.apps = AppManagement()
        self.metrics = InMemoryMetricsRepository()
        # per-request agent deadline (reference: the dashboard apiClient's
        # configurable http timeouts). An agent's FIRST hit on a stats
        # command jit-compiles its snapshot — allow overriding where 3 s
        # of compile is realistic (cold agents, loaded hosts).
        if agent_timeout_s is None:
            agent_timeout_s = float(
                os.environ.get("SENTINEL_DASH_AGENT_TIMEOUT_S", "0") or 0)
        self.client = (SentinelApiClient(timeout_s=agent_timeout_s)
                       if agent_timeout_s > 0 else SentinelApiClient())
        self.fetcher = MetricFetcher(self.apps, self.metrics,
                                     self.client, clock=clock)
        self.auth = AuthService(username, password)
        self.rules: Dict[str, RuleRepository] = {
            t: RuleRepository() for t in RULE_TYPES}
        # v2 pluggable rule pipeline (DynamicRuleProvider/Publisher SPI):
        # types with a registered pair read/publish through a config center
        # instead of direct machine push; agents pull the same store via a
        # datasource (rulepipeline.py)
        self.rule_pipeline = RulePipelineRegistry()
        self._clock = clock

    def set_rule_pipeline(self, rtype: str, provider=None,
                          publisher=None) -> None:
        """Install a v2 provider/publisher pair for one rule type
        (``FlowRuleApiProvider`` → config-center variant swap)."""
        self.rule_pipeline.set_pipeline(rtype, provider, publisher)

    def _now_ms(self) -> int:
        import time
        return (self._clock.now_ms() if self._clock is not None
                else int(time.time() * 1000))

    # --------------------------------------------------------- heartbeats
    def receive_heartbeat(self, params: Dict[str, str]) -> dict:
        app = params.get("app", "")
        ip = params.get("ip", "")
        if not app or not ip:
            return _fail("app and ip are required")
        m = MachineInfo(
            app=app, hostname=params.get("hostname", ""), ip=ip,
            port=int(params.get("port", "8719") or 8719),
            app_type=int(params.get("app_type", "0") or 0),
            version=params.get("v", ""),
            heartbeat_version=int(params.get("version", "0") or 0),
            last_heartbeat_ms=self._now_ms(),
            exporter_port=int(params.get("exporterPort", "0") or 0))
        self.apps.register(m)
        return _ok("success")

    # --------------------------------------------------------- rule CRUD
    def _machine(self, app: str, ip: str = "",
                 port: int = 0) -> Optional[MachineInfo]:
        if ip and port:
            return self.apps.get_machine(app, ip, port)
        return self.apps.first_healthy(app, self._now_ms())

    def query_rules(self, rtype: str, app: str, ip: str = "",
                    port: int = 0) -> dict:
        provider = self.rule_pipeline.provider(rtype)
        if provider is not None:
            # v2: the config center is the source of truth
            try:
                raw = provider.get_rules(app)
            except Exception as exc:
                return _fail(f"rule provider failed: {exc}")
            m = self._machine(app, ip, port) or MachineInfo(
                app=app, hostname="", ip="", port=0)
        else:
            m = self._machine(app, ip, port)
            if m is None:
                return _fail(f"no healthy machine for app {app}")
            try:
                raw = self.client.fetch_rules(m.ip, m.port, rtype)
            except AgentUnreachable as exc:
                return _fail(str(exc))
        repo = self.rules[rtype]
        known = {json.dumps(e.rule, sort_keys=True): e.id
                 for e in repo.find_by_app(app)}
        entities = []
        for r in raw:
            ent = RuleEntity(app=app, ip=m.ip, port=m.port, rule=r)
            ent.id = known.get(json.dumps(r, sort_keys=True), 0)
            entities.append(ent)
        entities = repo.replace_app(app, entities)
        return _ok([e.to_dict() for e in entities])

    def publish_rules(self, rtype: str, app: str) -> bool:
        rules = [e.rule for e in self.rules[rtype].find_by_app(app)]
        publisher = self.rule_pipeline.publisher(rtype)
        if publisher is not None:
            # v2: publish to the config center; agents converge by pulling
            # it through their datasource (no direct machine push)
            try:
                publisher.publish(app, rules)
                return True
            except Exception as exc:
                from sentinel_tpu.core.logs import record_log
                record_log().warning("rule publisher failed: %r", exc)
                return False
        ok = True
        machines = self.apps.healthy_machines(app, self._now_ms())
        if not machines:
            return False
        for m in machines:
            try:
                ok = self.client.set_rules(m.ip, m.port, rtype, rules) and ok
            except AgentUnreachable:
                ok = False
        return ok

    @staticmethod
    def _canonical(rtype: str, rule: Dict[str, Any]) -> Dict[str, Any]:
        """Round-trip through the rule codec so stored dicts carry every
        field with defaults — identical to what agents echo back from
        ``getRules`` (otherwise re-pulls can't match repo ids)."""
        try:
            if rtype == "gatewayFlow":
                from sentinel_tpu.gateway import codec as gw
                return gw.gateway_rule_to_dict(gw.gateway_rule_from_dict(rule))
            if rtype == "gatewayApi":
                from sentinel_tpu.gateway import codec as gw
                return gw.api_definition_to_dict(
                    gw.api_definition_from_dict(rule))
            from sentinel_tpu.rules import codec
            return json.loads(codec.rules_to_json(
                rtype, codec.rules_from_json(rtype, json.dumps([rule]))))[0]
        except (ValueError, KeyError, TypeError):
            return rule

    def add_rule(self, rtype: str, body: Dict[str, Any]) -> dict:
        app = body.pop("app", "")
        if not app:
            return _fail("app is required")
        ip, port = body.pop("ip", ""), int(body.pop("port", 0) or 0)
        body.pop("id", None)
        ent = self.rules[rtype].save(
            RuleEntity(app=app, ip=ip, port=port,
                       rule=self._canonical(rtype, body)))
        if not self.publish_rules(rtype, app):
            return _fail("rule saved but publish to machines failed",
                         code=-2) | {"data": ent.to_dict()}
        return _ok(ent.to_dict())

    def update_rule(self, rtype: str, rule_id: int,
                    body: Dict[str, Any]) -> dict:
        repo = self.rules[rtype]
        ent = repo.find(rule_id)
        if ent is None:
            return _fail(f"rule {rule_id} not found")
        for k in ("app", "id", "ip", "port"):
            body.pop(k, None)
        ent.rule.update(body)
        ent.rule = self._canonical(rtype, ent.rule)
        repo.save(ent)
        if not self.publish_rules(rtype, ent.app):
            return _fail("rule saved but publish to machines failed", code=-2)
        return _ok(ent.to_dict())

    def delete_rule(self, rtype: str, rule_id: int) -> dict:
        ent = self.rules[rtype].delete(rule_id)
        if ent is None:
            return _fail(f"rule {rule_id} not found")
        if not self.publish_rules(rtype, ent.app):
            return _fail("rule deleted but publish to machines failed",
                         code=-2)
        return _ok(rule_id)

    # --------------------------------------------------------- metrics
    def query_metrics(self, app: str, resource: str, start_ms: int,
                      end_ms: int) -> dict:
        ents = self.metrics.query(app, resource, start_ms, end_ms)
        return _ok([e.to_dict() for e in ents])

    def top_resources(self, app: str) -> dict:
        return _ok(self.metrics.list_resources(app))

    # --------------------------------------------------------- cluster
    def cluster_state(self, app: str) -> dict:
        out = []
        for m in self.apps.healthy_machines(app, self._now_ms()):
            try:
                st = self.client.get_cluster_mode(m.ip, m.port)
            except AgentUnreachable:
                st = {"mode": -1}
            if st.get("mode") == 1:
                # enrich server machines with live token-server info
                # (connected count, idle seconds — cluster/server/info)
                try:
                    info = self.client.fetch_cluster_server_info(m.ip, m.port)
                    st.setdefault("connectedCount",
                                  info.get("connectedCount"))
                    st.setdefault("idleSeconds", info.get("idleSeconds"))
                except AgentUnreachable:
                    pass
            st.update(ip=m.ip, port=m.port)
            out.append(st)
        return _ok(out)

    def set_cluster_mode(self, app: str, ip: str, port: int,
                         mode: int) -> dict:
        try:
            ok = self.client.set_cluster_mode(ip, port, mode)
        except AgentUnreachable as exc:
            return _fail(str(exc))
        return _ok(ok)

    def cluster_server_config(self, ip: str, port: int,
                              namespace: str = "") -> dict:
        """Token-server config view (reference
        ``cluster_app_server_manage`` screen): flow geometry +
        namespaceSet + transport, or one namespace's maxAllowedQps."""
        try:
            return _ok(self.client.fetch_cluster_server_config(
                ip, port, namespace))
        except AgentUnreachable as exc:
            return _fail(str(exc))

    def set_cluster_server_config(self, ip: str, port: int,
                                  namespace: str = "",
                                  max_allowed_qps: Optional[float] = None,
                                  namespaces: Optional[list] = None) -> dict:
        """Apply a server-config edit: the namespace set, the
        per-namespace QPS ceiling, or both in one call.

        The two writes are NOT transactional on the agent: a flow-config
        failure after the namespace set already landed reports partial
        success naming what applied and what didn't, so the operator
        re-submits only the failed half instead of assuming a clean
        rollback."""
        ns_applied = False
        try:
            if namespaces is not None:
                if not self.client.set_cluster_server_namespace_set(
                        ip, port, [str(n) for n in namespaces]):
                    return _fail("modify namespace set rejected")
                ns_applied = True
            if max_allowed_qps is not None:
                if not namespace:
                    return self._maybe_partial(
                        ns_applied, "namespace required for maxAllowedQps")
                if not self.client.set_cluster_server_flow_config(
                        ip, port, namespace, float(max_allowed_qps)):
                    return self._maybe_partial(
                        ns_applied, "modify flow config rejected")
        except AgentUnreachable as exc:
            return self._maybe_partial(ns_applied, str(exc))
        return _ok("success")

    @staticmethod
    def _maybe_partial(ns_applied: bool, msg: str) -> dict:
        if ns_applied:
            return _fail(
                "partial success: namespace set applied, but flow config "
                f"did not: {msg}")
        return _fail(msg)

    def cluster_assign(self, app: str, server_ip: str, server_port: int,
                       request_timeout_ms: int = 10_000) -> dict:
        """One-click topology (reference ``ClusterAssignService``): make the
        named machine the token server, then bind every other healthy
        machine of the app as a client of it."""
        server_machine = self.apps.get_machine(app, server_ip, server_port)
        if server_machine is None:
            return _fail(f"machine {server_ip}:{server_port} not registered")
        try:
            if not self.client.set_cluster_mode(server_ip, server_port, 1):
                return _fail("failed to switch server machine to SERVER mode")
            state = self.client.get_cluster_mode(server_ip, server_port)
        except AgentUnreachable as exc:
            return _fail(str(exc))
        token_port = int(state.get("serverPort", 0) or 0)
        if not token_port:
            return _fail("server machine reports no token-server port")
        bound, failed = [], []
        for m in self.apps.healthy_machines(app, self._now_ms()):
            if m.ip == server_ip and m.port == server_port:
                continue
            try:
                # generous default timeout: the server engine's first step
                # jit-compiles for seconds; the reference's 20 ms assumes a
                # warm JVM (clients can be retuned later via the same cmd)
                ok = (self.client.set_cluster_client_config(
                          m.ip, m.port, server_ip, token_port,
                          request_timeout=request_timeout_ms)
                      and self.client.set_cluster_mode(m.ip, m.port, 0))
            except AgentUnreachable:
                ok = False
            (bound if ok else failed).append(f"{m.ip}:{m.port}")
        return _ok({"server": f"{server_ip}:{server_port}",
                    "tokenPort": token_port,
                    "clients": bound, "failed": failed})


class _Handler(BaseHTTPRequestHandler):
    dash: Dashboard
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------ helpers
    def _send(self, status: int, payload: bytes,
              ctype: str = "application/json; charset=utf-8",
              extra: Optional[List[Tuple[str, str]]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (extra or []):
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _json(self, obj: dict, status: int = 200,
              extra: Optional[List[Tuple[str, str]]] = None) -> None:
        self._send(status, json.dumps(obj).encode("utf-8"), extra=extra)

    def _body_params(self, body: bytes) -> Dict[str, Any]:
        ctype = self.headers.get("Content-Type", "")
        if not body:
            return {}
        if "application/json" in ctype:
            try:
                obj = json.loads(body.decode("utf-8"))
                return obj if isinstance(obj, dict) else {}
            except ValueError:
                return {}
        return {k: v[-1] for k, v in
                urllib.parse.parse_qs(body.decode("utf-8")).items()}

    def _cookie_token(self) -> Optional[str]:
        cookie = self.headers.get("Cookie", "")
        m = re.search(r"sentinel_session=([^;\s]+)", cookie)
        return m.group(1) if m else None

    # ------------------------------------------------------------ routing
    def _route(self, method: str, body: bytes) -> None:
        d = self.dash
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        q = {k: v[-1] for k, v in
             urllib.parse.parse_qs(parsed.query).items()}
        if not d.auth.exempt(path) and not d.auth.check(self._cookie_token()):
            # 200 + code=401 envelope: the reference AuthFilter redirects, the
            # SPA keys off the envelope code instead
            self._json(_fail("login required", code=401))
            return

        if method == "POST" and path == "/registry/machine":
            params = dict(q)
            params.update({k: str(v) for k, v in
                           self._body_params(body).items()})
            self._json(d.receive_heartbeat(params))
            return
        if method == "POST" and path == "/auth/login":
            p = self._body_params(body)
            token = d.auth.login(str(p.get("username", "")),
                                 str(p.get("password", "")))
            if token is None:
                self._json(_fail("invalid credentials", code=401))
            else:
                self._json(_ok({"username": d.auth.username}), extra=[
                    ("Set-Cookie",
                     f"sentinel_session={token}; Path=/; HttpOnly")])
            return
        if method == "POST" and path == "/auth/logout":
            token = self._cookie_token()
            if token:
                d.auth.logout(token)
            self._json(_ok())
            return
        if method == "GET" and path == "/auth/check":
            self._json(_ok({"loggedIn":
                            d.auth.check(self._cookie_token())}))
            return
        if method == "GET" and path == "/app/names.json":
            self._json(_ok(d.apps.app_names()))
            return
        m = re.fullmatch(r"/app/([^/]+)/machines\.json", path)
        if method == "GET" and m:
            now = d._now_ms()
            self._json(_ok([mi.to_dict(now) for mi in
                            d.apps.machines(m.group(1))]))
            return
        m = re.fullmatch(r"/app/([^/]+)/machine/remove\.json", path)
        if method == "POST" and m:
            p = self._body_params(body)
            ok = d.apps.remove_machine(m.group(1), str(p.get("ip", "")),
                                       int(p.get("port", 0) or 0))
            self._json(_ok("success") if ok
                       else _fail("machine not found"))
            return
        if method == "GET" and path == "/metric/resources.json":
            self._json(d.top_resources(q.get("app", "")))
            return
        if method == "GET" and path == "/metric/queryByAppAndResource.json":
            self._json(d.query_metrics(
                q.get("app", ""), q.get("identity", ""),
                int(q.get("startTime", "0") or 0),
                int(q.get("endTime", "0") or 0)))
            return
        if method == "GET" and path == "/resource/machineResource.json":
            try:
                nodes = d.client.fetch_cluster_nodes(
                    q.get("ip", ""), int(q.get("port", "0") or 0))
                self._json(_ok(nodes))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/resource/origin.json":
            try:
                self._json(_ok(d.client.fetch_origin_stats(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    q.get("id", ""))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/resource/jsonTree.json":
            try:
                self._json(_ok(d.client.fetch_json_tree(
                    q.get("ip", ""), int(q.get("port", "0") or 0))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/systemStatus.json":
            try:
                self._json(_ok(d.client.fetch_system_status(
                    q.get("ip", ""), int(q.get("port", "0") or 0))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/obs/telemetry.json":
            try:
                self._json(_ok(d.client.fetch_obs(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    spans=int(q.get("spans", "128") or 128),
                    events=int(q.get("events", "64") or 64),
                    trace=q.get("trace", ""))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/obs/topk.json":
            # hot-resource telemetry: device-side sharded top-K + the
            # per-second timeline ring (obs/telemetry.py via the agent's
            # ``topk`` command)
            try:
                self._json(_ok(d.client.fetch_topk(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    timeline=int(q.get("timeline", "60") or 60),
                    tick=q.get("tick", "") in ("1", "true"))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/obs/control.json":
            # overload-controller state + applied-action audit tail
            # (control/loop.py via the agent's ``control`` command)
            try:
                self._json(_ok(d.client.fetch_control(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    actions=int(q.get("actions", "32") or 32),
                    tick=q.get("tick", "") in ("1", "true"))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/obs/traces.json":
            # request-scoped tracing: ?id= proxies one causal chain as a
            # Chrome-trace-event document; without id, the flight
            # recorder's pinned-record index (docs/OBSERVABILITY.md)
            try:
                self._json(_ok(d.client.fetch_trace(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    trace_id=q.get("id", ""))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/cluster/state.json":
            self._json(d.cluster_state(q.get("app", "")))
            return
        if method == "GET" and path == "/cluster/metrics.json":
            # token-server per-flow metrics; namespace defaults to the app
            # name (ClusterCoordinator's default namespace)
            try:
                self._json(_ok(d.client.fetch_cluster_server_metrics(
                    q.get("ip", ""), int(q.get("port", "0") or 0),
                    q.get("namespace", "") or q.get("app", ""))))
            except AgentUnreachable as exc:
                self._json(_fail(str(exc)))
            return
        if method == "GET" and path == "/cluster/serverConfig.json":
            self._json(d.cluster_server_config(
                q.get("ip", ""), int(q.get("port", "0") or 0),
                q.get("namespace", "")))
            return
        if method == "POST" and path == "/cluster/serverConfig":
            p = self._body_params(body)
            qps = p.get("maxAllowedQps")
            nss = p.get("namespaces")
            if isinstance(nss, str):
                nss = [s.strip() for s in nss.split(",") if s.strip()]
            if nss is not None and not nss:
                # an empty set would silently stop serving every namespace
                # while the UI still shows the app-name fallback
                self._json(_fail("namespace set must not be empty"))
                return
            self._json(d.set_cluster_server_config(
                str(p.get("ip", "")), int(p.get("port", 0) or 0),
                namespace=str(p.get("namespace", "") or ""),
                max_allowed_qps=(float(qps) if qps not in (None, "")
                                 else None),
                namespaces=nss))
            return
        if method == "POST" and path == "/cluster/mode":
            p = self._body_params(body)
            self._json(d.set_cluster_mode(
                str(p.get("app", "")), str(p.get("ip", "")),
                int(p.get("port", 0) or 0), int(p.get("mode", 0) or 0)))
            return
        if method == "POST" and path == "/cluster/assign":
            p = self._body_params(body)
            self._json(d.cluster_assign(
                str(p.get("app", "")), str(p.get("serverIp", "")),
                int(p.get("serverPort", 0) or 0),
                request_timeout_ms=int(p.get("requestTimeout",
                                             10_000) or 10_000)))
            return

        m = re.fullmatch(r"/v1/([^/]+)/rules", path)
        if method == "GET" and m:
            rtype = m.group(1)
            if rtype not in RULE_TYPES:
                self._json(_fail(f"unknown rule type {rtype}"), status=404)
                return
            self._json(d.query_rules(rtype, q.get("app", ""),
                                     q.get("ip", ""),
                                     int(q.get("port", "0") or 0)))
            return
        m = re.fullmatch(r"/v1/([^/]+)/rule(?:/(\d+))?", path)
        if m:
            rtype, rid = m.group(1), m.group(2)
            if rtype not in RULE_TYPES:
                self._json(_fail(f"unknown rule type {rtype}"), status=404)
                return
            if method == "POST" and rid is None:
                self._json(d.add_rule(rtype, self._body_params(body)))
                return
            if method == "PUT" and rid is not None:
                self._json(d.update_rule(rtype, int(rid),
                                         self._body_params(body)))
                return
            if method == "DELETE" and rid is not None:
                self._json(d.delete_rule(rtype, int(rid)))
                return

        if method == "GET" and path in ("/", "/index.html"):
            page = _STATIC_DIR / "index.html"
            self._send(200, page.read_bytes(),
                       ctype="text/html; charset=utf-8")
            return
        if method == "GET" and path.startswith("/static/"):
            f = _STATIC_DIR / path[len("/static/"):]
            if f.is_file() and _STATIC_DIR in f.resolve().parents:
                ctype = ("text/css" if f.suffix == ".css"
                         else "application/javascript" if f.suffix == ".js"
                         else "application/octet-stream")
                self._send(200, f.read_bytes(), ctype=ctype)
                return
        self._json(_fail(f"no route {method} {path}"), status=404)

    def _route_safe(self, method: str, body: bytes) -> None:
        try:
            self._route(method, body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as exc:   # malformed params must yield a response
            try:
                self._json(_fail(f"internal error: {exc}", code=500),
                           status=500)
            except OSError:
                pass

    def do_GET(self) -> None:  # noqa: N802
        self._route_safe("GET", b"")

    def _with_body(self, method: str) -> None:
        length = int(self.headers.get("Content-Length", "0") or 0)
        self._route_safe(method, self.rfile.read(length) if length else b"")

    def do_POST(self) -> None:  # noqa: N802
        self._with_body("POST")

    def do_PUT(self) -> None:  # noqa: N802
        self._with_body("PUT")

    def do_DELETE(self) -> None:  # noqa: N802
        self._with_body("DELETE")

    def log_message(self, fmt, *args):
        pass


class DashboardServer:
    """Owns the HTTP server thread + the metric fetcher loop."""

    def __init__(self, dashboard: Optional[Dashboard] = None,
                 host: str = "0.0.0.0", port: int = 8080, **kw):
        self.dashboard = dashboard or Dashboard(**kw)
        self.host = host
        self.requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, *, fetch: bool = True) -> int:
        handler = type("BoundDashHandler", (_Handler,),
                       {"dash": self.dashboard})
        self._server = ThreadingHTTPServer(
            (self.host, self.requested_port), handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sentinel-dashboard")
        self._thread.start()
        if fetch:
            self.dashboard.fetcher.start()
        return self.port

    def stop(self) -> None:
        self.dashboard.fetcher.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
