"""Command surface mounted into a host web app (reference
``sentinel-transport-spring-mvc``'s ``SentinelApiHandlerMapping`` /
``sentinel-transport-netty-http`` — the command center served by the
application's own HTTP stack instead of a dedicated port).

``command_wsgi_app(center)`` returns a WSGI callable and
``command_asgi_app(center)`` an ASGI callable; mount either under a path
prefix of your app (e.g. ``/sentinel``) and point the dashboard's machine
port at the app port. Request semantics match
:class:`~sentinel_tpu.transport.http_server.SimpleHttpCommandCenter`:
command name = URL path, params = query string merged with a
form-encoded body, response = ``text/plain`` command result.
"""

from __future__ import annotations

import urllib.parse
from typing import Optional

from sentinel_tpu.transport.command import (
    CommandCenter, CommandRequest, CommandResponse,
)


def _run(center: CommandCenter, path: str, query: str, body: bytes,
         ctype: str) -> CommandResponse:
    name = path.strip("/")
    params = {k: v[-1] for k, v in urllib.parse.parse_qs(query).items()}
    if body and "application/x-www-form-urlencoded" in ctype:
        try:
            for k, v in urllib.parse.parse_qs(body.decode("utf-8")).items():
                params[k] = v[-1]
        except UnicodeDecodeError:
            return CommandResponse.of_failure("invalid request body", 400)
    if not name:
        return CommandResponse.of_failure("Command name cannot be empty", 400)
    return center.handle(name, CommandRequest(parameters=params, body=body))


def command_wsgi_app(center: CommandCenter, prefix: str = ""):
    """WSGI app serving the command center. ``prefix`` is stripped from
    ``PATH_INFO`` when the host framework doesn't already do so."""

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "")
        if prefix and path.startswith(prefix):
            path = path[len(prefix):]
        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        body = environ["wsgi.input"].read(length) if length else b""
        resp = _run(center, path, environ.get("QUERY_STRING", ""), body,
                    environ.get("CONTENT_TYPE", ""))
        payload = resp.result.encode("utf-8")
        status = "200 OK" if resp.success else f"{resp.code} ERROR"
        start_response(status, [
            ("Content-Type", "text/plain; charset=utf-8"),
            ("Content-Length", str(len(payload)))])
        return [payload]

    return app


def command_asgi_app(center: CommandCenter, prefix: str = ""):
    """ASGI (http-scope) app serving the command center."""

    async def app(scope, receive, send):
        # ASGI frameworks route lifespan (when mounted at an app root) and
        # websocket scopes to mounted apps too — complete/close them cleanly
        # instead of surfacing a server-side exception.
        if scope["type"] == "lifespan":
            while True:
                msg = await receive()
                if msg["type"] == "lifespan.startup":
                    await send({"type": "lifespan.startup.complete"})
                elif msg["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
        if scope["type"] == "websocket":
            await receive()                     # websocket.connect
            await send({"type": "websocket.close", "code": 1000})
            return
        if scope["type"] != "http":
            return                              # unknown scope: ignore
        path = scope.get("path", "")
        if prefix and path.startswith(prefix):
            path = path[len(prefix):]
        body = b""
        while True:
            msg = await receive()
            body += msg.get("body", b"")
            if not msg.get("more_body"):
                break
        headers = {k.decode("latin-1").lower(): v.decode("latin-1")
                   for k, v in scope.get("headers", [])}
        resp = _run(center, path,
                    scope.get("query_string", b"").decode("latin-1"),
                    body, headers.get("content-type", ""))
        payload = resp.result.encode("utf-8")
        await send({"type": "http.response.start",
                    "status": 200 if resp.success else resp.code,
                    "headers": [
                        (b"content-type", b"text/plain; charset=utf-8"),
                        (b"content-length",
                         str(len(payload)).encode("latin-1"))]})
        await send({"type": "http.response.body", "body": payload})

    return app
