"""Asyncio HTTP command frontend — the nonblocking command-transport
variant (reference ``sentinel-transport-netty-http/.../
NettyHttpCommandCenter.java:36`` + ``HttpServerHandler``: an event-loop
server beside the thread-per-connection simple-http one).

Why it exists: the threaded :class:`SimpleHttpCommandCenter` dedicates a
thread per connection, so a handful of slow-loris clients (bytes trickling
into the header parser) pin the pool and starve the ops surface. Here one
event loop multiplexes all connections; per-connection READ DEADLINES and
size caps bound what any client can hold open, and command handlers run in
a small executor so a blocking handler can't stall the loop.

Same dispatch contract as the threaded server: ``GET /command?k=v`` and
``POST`` form bodies → :class:`CommandRequest` → ``CommandCenter.handle``.
Port conflicts auto-increment (``SimpleHttpCommandCenter.java:48-80``).
"""

from __future__ import annotations

import asyncio
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from sentinel_tpu.transport.command import (
    CommandCenter, CommandRequest, CommandResponse,
)
from sentinel_tpu.transport.http_server import MAX_PORT_ATTEMPTS

MAX_HEADER_BYTES = 16 * 1024
MAX_BODY_BYTES = 4 * 1024 * 1024
READ_TIMEOUT_S = 10.0       # slow-loris bound: full request must arrive
KEEPALIVE_TIMEOUT_S = 30.0  # idle keep-alive connections are reaped


class AsyncHttpCommandCenter:
    """Owns the event loop thread; ``port`` reflects the bound port."""

    def __init__(self, center: CommandCenter, host: str = "0.0.0.0",
                 port: int = 8719, read_timeout_s: float = READ_TIMEOUT_S,
                 max_workers: int = 4):
        self.center = center
        self.host = host
        self.requested_port = port
        self.port: Optional[int] = None
        self.read_timeout_s = read_timeout_s
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="sentinel-async-cmd")
        self._started = threading.Event()
        self._start_err: Optional[BaseException] = None

    # ---------------- connection handling (on the loop) ----------------

    async def _read_request(self, reader: asyncio.StreamReader,
                            first: bool):
        """→ (method, path, headers, body) or None on clean EOF."""
        # the request LINE may wait (keep-alive idle), but once bytes flow
        # the whole head must arrive within read_timeout_s
        line = await asyncio.wait_for(
            reader.readline(),
            KEEPALIVE_TIMEOUT_S if not first else self.read_timeout_s)
        if not line:
            return None
        async def _head():
            headers = {}
            total = len(line)
            while True:
                h = await reader.readline()
                total += len(h)
                if total > MAX_HEADER_BYTES:
                    raise ValueError("header too large")
                if h in (b"\r\n", b"\n", b""):
                    return headers
                k, _, v = h.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
        headers = await asyncio.wait_for(_head(), self.read_timeout_s)
        try:
            method, path, _ = line.decode("latin-1").split(None, 2)
        except ValueError:
            raise ValueError("bad request line")
        length = int(headers.get("content-length", "0") or 0)
        if length > MAX_BODY_BYTES:
            raise ValueError("body too large")
        body = b""
        if length:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          self.read_timeout_s)
        return method, path, headers, body

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            first = True
            while True:
                try:
                    req = await self._read_request(reader, first)
                except (asyncio.TimeoutError, ValueError,
                        asyncio.IncompleteReadError):
                    break               # slow/malformed client: reap it
                if req is None:
                    break
                first = False
                method, path, headers, body = req
                parsed = urllib.parse.urlparse(path)
                name = parsed.path.strip("/")
                params = {k: v[-1] for k, v in
                          urllib.parse.parse_qs(parsed.query).items()}
                ctype = headers.get("content-type", "")
                bad = None
                if body and "application/x-www-form-urlencoded" in ctype:
                    try:
                        for k, v in urllib.parse.parse_qs(
                                body.decode("utf-8")).items():
                            params[k] = v[-1]
                    except UnicodeDecodeError:
                        bad = CommandResponse.of_failure(
                            "invalid request body", 400)
                if bad is not None:
                    resp = bad
                elif not name:
                    resp = CommandResponse.of_failure(
                        "Command name cannot be empty", 400)
                else:
                    # handlers may block (engine locks, device steps):
                    # keep the loop free. CommandCenter.handle already
                    # converts handler exceptions to 500 responses; this
                    # catch covers only executor-infrastructure failures
                    # (e.g. pool shutdown during stop()) so the client
                    # still gets a response instead of a dropped
                    # connection + unretrieved-exception traceback.
                    try:
                        resp = await asyncio.get_running_loop() \
                            .run_in_executor(
                                self._pool, self.center.handle, name,
                                CommandRequest(parameters=params,
                                               body=body))
                    except Exception as exc:
                        resp = CommandResponse.of_failure(
                            f"command handler error: {exc!r}", 500)
                payload = resp.result.encode("utf-8")
                code = resp.code if not resp.success else 200
                keep = headers.get("connection", "keep-alive") != "close"
                head = (f"HTTP/1.1 {code} X\r\n"
                        f"Content-Type: text/plain; charset=utf-8\r\n"
                        f"Content-Length: {len(payload)}\r\n"
                        f"Connection: {'keep-alive' if keep else 'close'}"
                        f"\r\n\r\n")
                writer.write(head.encode("latin-1") + payload)
                await writer.drain()
                if not keep:
                    break
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    # ---------------- lifecycle (host threads) ----------------

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def _start():
            last: Optional[OSError] = None
            for attempt in range(MAX_PORT_ATTEMPTS):
                try:
                    return await asyncio.start_server(
                        self._handle_conn, self.host,
                        self.requested_port + attempt)
                except OSError as exc:
                    last = exc
            raise OSError(
                f"no free command port in [{self.requested_port}, "
                f"{self.requested_port + MAX_PORT_ATTEMPTS})") from last

        try:
            self._server = loop.run_until_complete(_start())
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as exc:
            self._start_err = exc
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            self._server.close()
            loop.run_until_complete(self._server.wait_closed())
            loop.close()

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True,
            name="sentinel-async-command-center")
        self._thread.start()
        self._started.wait(timeout=10)
        if self._start_err is not None:
            raise self._start_err
        assert self.port is not None
        return self.port

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._thread is not None:
                self._thread.join(timeout=5)
            self._loop = None
        self._pool.shutdown(wait=False)
