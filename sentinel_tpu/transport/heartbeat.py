"""Heartbeat sender (reference
``sentinel-transport-simple-http/.../SimpleHttpHeartbeatSender.java`` +
``HeartbeatMessage.java``).

Periodically POSTs the agent's identity to the dashboard's
``/registry/machine`` endpoint so it discovers live machines. Message fields
mirror ``HeartbeatMessage.java:1-30``: hostname, ip, transport port, app
name/type, framework + spec version, current time.
"""

from __future__ import annotations

import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from sentinel_tpu import __version__

HEARTBEAT_PATH = "/registry/machine"   # TransportConfig.java:41
DEFAULT_INTERVAL_MS = 10_000


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.254.254.254", 1))   # no packets actually sent
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(self, dashboard_addr: str, *, app_name: str,
                 app_type: int = 0, api_port: int = 8719,
                 interval_ms: int = DEFAULT_INTERVAL_MS,
                 clock=None, exporter_port: Optional[int] = None):
        """``dashboard_addr`` is ``host:port`` (csp.sentinel.dashboard.server).
        ``exporter_port`` — when the app serves Prometheus ``/metrics``
        (metrics/exporter.py), advertise that port too so scrape targets
        can be discovered from dashboard machine discovery."""
        self.dashboard_addr = dashboard_addr
        self.app_name = app_name
        self.app_type = app_type
        self.api_port = api_port
        self.exporter_port = exporter_port
        self.interval_ms = interval_ms
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ok: bool = False
        self.sent_count = 0

    def message(self) -> dict:
        import time
        now = (self._clock.now_ms() if self._clock is not None
               else int(time.time() * 1000))
        msg = {
            "hostname": socket.gethostname(),
            "ip": _local_ip(),
            "port": str(self.api_port),
            "app": self.app_name,
            "app_type": str(self.app_type),
            "v": __version__,                    # heartbeat client version
            "version": str(now),
        }
        if self.exporter_port:
            msg["exporterPort"] = str(self.exporter_port)
        return msg

    def send_once(self, timeout: float = 3.0) -> bool:
        url = f"http://{self.dashboard_addr}{HEARTBEAT_PATH}"
        data = urllib.parse.urlencode(self.message()).encode("utf-8")
        try:
            with urllib.request.urlopen(url, data=data, timeout=timeout) as r:
                self.last_ok = 200 <= r.status < 300
        except (urllib.error.URLError, OSError):
            self.last_ok = False
        self.sent_count += 1
        return self.last_ok

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            # first beat inside the thread: start() must not block app
            # startup on an unreachable dashboard (connect can hang ~3 s)
            self.send_once()
            while not self._stop.wait(self.interval_ms / 1000.0):
                self.send_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sentinel-heartbeat")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
