"""HTTP command frontend (reference
``sentinel-transport-simple-http/.../SimpleHttpCommandCenter.java``).

A threaded stdlib HTTP server on the API port (default 8719) that parses
``GET /commandName?k=v`` and ``POST`` form bodies into
:class:`CommandRequest` and dispatches into the :class:`CommandCenter`.
Port conflicts auto-increment like the reference (tryServerSocket loop,
``SimpleHttpCommandCenter.java:48-80``).
"""

from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from sentinel_tpu.transport.command import (
    CommandCenter, CommandRequest, CommandResponse,
)

MAX_PORT_ATTEMPTS = 3  # PORT_UNINITIALIZED retry count in the reference


class _Handler(BaseHTTPRequestHandler):
    center: CommandCenter  # set on the subclass by SimpleHttpCommandCenter
    protocol_version = "HTTP/1.1"

    def _dispatch(self, body: bytes) -> None:
        parsed = urllib.parse.urlparse(self.path)
        name = parsed.path.strip("/")
        params = {k: v[-1] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
        ctype = self.headers.get("Content-Type", "")
        if body and "application/x-www-form-urlencoded" in ctype:
            try:
                decoded = body.decode("utf-8")
            except UnicodeDecodeError:
                resp = CommandResponse.of_failure("invalid request body", 400)
                payload = resp.result.encode("utf-8")
                self.send_response(400)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
            for k, v in urllib.parse.parse_qs(decoded).items():
                params[k] = v[-1]
        if not name:
            resp = CommandResponse.of_failure(
                "Command name cannot be empty", 400)
        else:
            resp = self.center.handle(
                name, CommandRequest(parameters=params, body=body))
        payload = resp.result.encode("utf-8")
        self.send_response(resp.code if not resp.success else 200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802
        self._dispatch(b"")

    def do_POST(self) -> None:  # noqa: N802
        length = int(self.headers.get("Content-Length", "0") or 0)
        self._dispatch(self.rfile.read(length) if length else b"")

    def log_message(self, fmt, *args):  # quiet; RecordLog covers diagnostics
        pass


class SimpleHttpCommandCenter:
    """Owns the server thread; ``port`` reflects the actually-bound port."""

    def __init__(self, center: CommandCenter, host: str = "0.0.0.0",
                 port: int = 8719):
        self.center = center
        self.host = host
        self.requested_port = port
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        handler = type("BoundHandler", (_Handler,), {"center": self.center})
        last_err: Optional[OSError] = None
        for attempt in range(MAX_PORT_ATTEMPTS):
            try:
                self._server = ThreadingHTTPServer(
                    (self.host, self.requested_port + attempt), handler)
                break
            except OSError as exc:
                last_err = exc
        if self._server is None:
            raise OSError(
                f"no free command port in "
                f"[{self.requested_port}, {self.requested_port + MAX_PORT_ATTEMPTS})"
            ) from last_err
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sentinel-command-center")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
