"""One-call agent transport bootstrap.

The reference binds its command server first and then stores the *actual*
port back into ``TransportConfig`` so heartbeats advertise the right address
after port auto-increment (``SimpleHttpCommandCenter.java:48-80`` +
``TransportConfig.setRuntimePort``). This helper reproduces that ordering:
start command center → learn bound port → advertise it in both the
heartbeat message and the ``basicInfo`` command.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from sentinel_tpu.transport.command import CommandCenter
from sentinel_tpu.transport.handlers import (
    ClusterModeState, register_default_handlers,
)
from sentinel_tpu.transport.heartbeat import HeartbeatSender
from sentinel_tpu.transport.http_server import SimpleHttpCommandCenter


@dataclasses.dataclass
class TransportRuntime:
    center: CommandCenter
    http: object            # SimpleHttpCommandCenter | AsyncHttpCommandCenter
    heartbeat: Optional[HeartbeatSender]
    cluster_state: ClusterModeState
    port: int
    metric_timer: Optional[object] = None
    cadence: Optional[object] = None    # serving.CadenceScheduler (r16)

    def stop(self) -> None:
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.metric_timer is not None:
            self.metric_timer.stop()
        if self.cadence is not None:
            # join the cadence daemon here, not just at Sentinel.close():
            # embedders that stop the transport without closing the
            # engine must not leave a device-dispatching thread running
            # into interpreter teardown
            self.cadence.stop()
        self.http.stop()


def start_transport(sentinel, *, host: str = "0.0.0.0", port: int = 8719,
                    dashboard_addr: Optional[str] = None,
                    metric_searcher=None, writable_registry=None,
                    heartbeat_interval_ms: int = 10_000,
                    metric_log: bool = True,
                    gateway_manager=None, api_definition_manager=None,
                    clock=None, async_server: bool = False,
                    exporter_port: Optional[int] = None) -> TransportRuntime:
    """Start the HTTP command center (with port auto-increment) and, when a
    dashboard address is given, a heartbeat loop advertising the port that
    was actually bound.

    ``metric_log=True`` (the default, matching the reference where the
    metric-file timer always runs — ``MetricTimerListener`` is started by
    FlowRuleManager's static init) also wires the metric pipeline: a 1 s
    writer flushing window snapshots to the app's metric log plus a searcher
    serving the ``metric`` command, which is what the dashboard's fetcher
    polls for the realtime charts. Pass an explicit ``metric_searcher`` (or
    ``metric_log=False``) to manage the pipeline yourself."""
    center = CommandCenter()
    extra: dict = {}
    metric_timer = None
    cadence = None
    if metric_searcher is None and metric_log:
        from sentinel_tpu.metrics.searcher import MetricSearcher
        from sentinel_tpu.metrics.timer import MetricTimerListener
        from sentinel_tpu.metrics.writer import form_metric_file_name
        metric_timer = MetricTimerListener(
            sentinel, flush_interval_sec=sentinel.cfg.metric_flush_interval_sec)
        metric_timer.start()
        metric_searcher = MetricSearcher(
            sentinel.cfg.metric_dir(),
            form_metric_file_name(sentinel.cfg.app_name))
        # attach the sampled block-event log (obs/eventlog.py) and the
        # SLO flight recorder's <app>-trace log (obs/flight.py) to the
        # same metric directory — both 1 s drains ride metric_timer.tick()
        obs = getattr(sentinel, "obs", None)
        if obs is not None:
            obs.block_events.configure(sentinel.cfg.metric_dir(),
                                       sentinel.cfg.app_name)
            obs.flight.configure(sentinel.cfg.metric_dir(),
                                 sentinel.cfg.app_name)
        # hot-resource telemetry (obs/telemetry.py): top-K second lines
        # ride the same rotation as <app>-metric. Since round 16 the
        # telemetry + tiering cadences share ONE CadenceScheduler thread
        # (serving.py): it arms both services' epilogue carries so
        # steady serving traffic runs the ticks inside the fused
        # dispatch, and only self-dispatches on idle gaps. Its drains
        # still overlap the dispatch pipeline rather than serializing
        # behind metric_timer.tick(). Stops via register_shutdown.
        telemetry = getattr(sentinel, "telemetry", None)
        if telemetry is not None and telemetry.enabled:
            telemetry.configure(sentinel.cfg.metric_dir(),
                                sentinel.cfg.app_name)
            from sentinel_tpu.serving import CadenceScheduler
            cadence = CadenceScheduler(sentinel)
            cadence.start()
    cstate = register_default_handlers(
        center, sentinel, metric_searcher=metric_searcher,
        extra_info=extra, writable_registry=writable_registry,
        gateway_manager=gateway_manager,
        api_definition_manager=api_definition_manager)
    if async_server:
        # nonblocking variant (NettyHttpCommandCenter analog): one event
        # loop, slow-loris-bounded — transport/async_http_server.py
        from sentinel_tpu.transport.async_http_server import (
            AsyncHttpCommandCenter,
        )
        http = AsyncHttpCommandCenter(center, host=host, port=port)
    else:
        http = SimpleHttpCommandCenter(center, host=host, port=port)
    bound = http.start()
    extra["apiPort"] = bound          # basicInfo reflects the bound port
    if exporter_port:
        extra["exporterPort"] = exporter_port

    hb = None
    if dashboard_addr:
        hb = HeartbeatSender(
            dashboard_addr, app_name=sentinel.cfg.app_name,
            app_type=sentinel.cfg.app_type, api_port=bound,
            interval_ms=heartbeat_interval_ms,
            clock=clock if clock is not None else sentinel.clock,
            exporter_port=exporter_port)
        hb.start()
    return TransportRuntime(center=center, http=http, heartbeat=hb,
                            cluster_state=cstate, port=bound,
                            metric_timer=metric_timer, cadence=cadence)
