"""Command handler registry (reference ``sentinel-transport-common``).

A command is ``name → handler(CommandRequest) → CommandResponse`` — the
reference's ``@CommandMapping`` annotated ``CommandHandler`` SPI
(``transport/command/CommandHandler.java``, dispatched by
``SimpleHttpCommandCenter``/``NettyHttpCommandCenter``). Handlers are plain
callables here; ``command_mapping`` attaches metadata and ``CommandCenter``
is the in-process registry the HTTP frontends dispatch into.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class CommandRequest:
    """Parsed request: query/body parameters + raw body."""

    parameters: Dict[str, str] = dataclasses.field(default_factory=dict)
    body: bytes = b""

    def param(self, name: str, default: str = "") -> str:
        return self.parameters.get(name, default)


@dataclasses.dataclass
class CommandResponse:
    success: bool
    result: str = ""
    code: int = 200

    @staticmethod
    def of_success(result: str) -> "CommandResponse":
        return CommandResponse(True, result)

    @staticmethod
    def of_failure(message: str, code: int = 400) -> "CommandResponse":
        return CommandResponse(False, message, code)


Handler = Callable[[CommandRequest], CommandResponse]


def command_mapping(name: str, desc: str = "") -> Callable[[Handler], Handler]:
    """Decorator analog of ``@CommandMapping(name=…, desc=…)``."""

    def wrap(fn: Handler) -> Handler:
        fn.command_name = name          # type: ignore[attr-defined]
        fn.command_desc = desc          # type: ignore[attr-defined]
        return fn

    return wrap


class CommandCenter:
    """Name → handler registry; thread-safe; shared by HTTP frontends."""

    def __init__(self) -> None:
        self._handlers: Dict[str, Handler] = {}
        self._descs: Dict[str, str] = {}
        self._interceptors: list = []     # CommandHandlerInterceptor SPI
        self._lock = threading.Lock()

    def add_interceptor(self, fn) -> None:
        """``CommandHandlerInterceptor`` analog: ``fn(name, request) →
        Optional[CommandResponse]`` runs before the handler; a non-None
        return short-circuits it (auth gates, audit logs, rate limits on
        the command plane itself)."""
        with self._lock:
            self._interceptors = self._interceptors + [fn]

    def register(self, fn: Handler, name: Optional[str] = None,
                 desc: Optional[str] = None) -> None:
        cmd = name or getattr(fn, "command_name", None)
        if not cmd:
            raise ValueError("handler has no command name")
        with self._lock:
            self._handlers[cmd] = fn
            self._descs[cmd] = desc or getattr(fn, "command_desc", "")

    def handler(self, name: str) -> Optional[Handler]:
        with self._lock:
            return self._handlers.get(name)

    def names(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._descs)

    def handle(self, name: str, request: CommandRequest) -> CommandResponse:
        fn = self.handler(name)
        if fn is None:
            return CommandResponse.of_failure(f"Unknown command `{name}`", 404)
        try:
            for interceptor in self._interceptors:   # copy-on-write list
                short = interceptor(name, request)
                if short is not None:
                    return short
            return fn(request)
        except Exception as exc:  # handler bug must not kill the server
            return CommandResponse.of_failure(f"internal error: {exc!r}", 500)
