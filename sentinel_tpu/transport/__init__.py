"""Command plane: HTTP command center + heartbeat (reference
``sentinel-transport/*`` rebuilt on the stdlib http server)."""

from sentinel_tpu.transport.command import (  # noqa: F401
    CommandCenter, CommandRequest, CommandResponse, command_mapping,
)
from sentinel_tpu.transport.handlers import register_default_handlers  # noqa: F401
from sentinel_tpu.transport.http_server import SimpleHttpCommandCenter  # noqa: F401
from sentinel_tpu.transport.heartbeat import HeartbeatSender  # noqa: F401
from sentinel_tpu.transport.bootstrap import (  # noqa: F401
    TransportRuntime, start_transport,
)
from sentinel_tpu.transport.mounted import (  # noqa: F401
    command_asgi_app, command_wsgi_app,
)
