"""The built-in command handlers (reference
``sentinel-transport-common/.../command/handler/*.java`` — the 18 commands
the dashboard drives agents with, SURVEY §2.4).

Each handler closes over a :class:`~sentinel_tpu.runtime.Sentinel` instance
(plus optional metric searcher / cluster hooks) and is registered into a
:class:`~sentinel_tpu.transport.command.CommandCenter`.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional

from sentinel_tpu import __version__
from sentinel_tpu.core.logs import record_log
from sentinel_tpu.core.registry import ENTRY_NODE_ROW
from sentinel_tpu.metrics.node import TOTAL_IN_RESOURCE_NAME
from sentinel_tpu.metrics.searcher import MetricSearcher
from sentinel_tpu.rules import codec
from sentinel_tpu.transport.command import (
    CommandCenter, CommandRequest, CommandResponse,
)

# ClusterStateManager.java: CLUSTER_NOT_STARTED=-1, CLIENT=0, SERVER=1
CLUSTER_NOT_STARTED = -1
CLUSTER_CLIENT = 0
CLUSTER_SERVER = 1

_MAX_METRIC_LINES = 12000  # SendMetricCommandHandler maxLines/FETCH cap


class ClusterModeState:
    """Per-process cluster mode cell (``ClusterStateManager`` analog).

    ``on_change(mode)`` hooks let the embedding app start/stop its token
    client/server when the dashboard flips the mode; client-config
    observers mirror ``ClusterClientConfigManager``'s ServerChangeObserver;
    ``info_provider`` lets the running server/client report live details
    (e.g. the bound token-server port) through ``getClusterMode``.
    """

    def __init__(self) -> None:
        self.mode = CLUSTER_NOT_STARTED
        self.last_modified_ms = 0
        self.client_config: Dict[str, Any] = {}
        self.info_provider: Optional[Callable[[], Dict[str, Any]]] = None
        self._observers: list = []
        self._config_observers: list = []

    def add_observer(self, fn: Callable[[int], None]) -> None:
        self._observers.append(fn)

    def add_config_observer(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        self._config_observers.append(fn)

    def set_mode(self, mode: int, now_ms: int = 0) -> None:
        self.mode = mode
        self.last_modified_ms = now_ms
        for fn in list(self._observers):
            fn(mode)

    def set_client_config(self, config: Dict[str, Any]) -> None:
        self.client_config = dict(config)
        for fn in list(self._config_observers):
            fn(self.client_config)


def register_default_handlers(
    center: CommandCenter,
    sentinel,
    *,
    metric_searcher: Optional[MetricSearcher] = None,
    cluster_state: Optional[ClusterModeState] = None,
    extra_info: Optional[Dict[str, Any]] = None,
    writable_registry=None,
    gateway_manager=None,
    api_definition_manager=None,
) -> ClusterModeState:
    """Bind the full default command surface for one Sentinel instance."""
    from sentinel_tpu.datasource.registry import default_registry

    s = sentinel
    cstate = cluster_state or ClusterModeState()
    wreg = writable_registry if writable_registry is not None else default_registry

    # ---- meta ------------------------------------------------------------

    def cmd_version(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(__version__)

    def cmd_api(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(json.dumps(
            [{"url": f"/{name}", "desc": desc}
             for name, desc in sorted(center.names().items())]))

    def cmd_basic_info(req: CommandRequest) -> CommandResponse:
        info = {
            "appName": s.cfg.app_name, "appType": s.cfg.app_type,
            "version": __version__, "apiPort": s.cfg.api_port,
            "maxResources": s.cfg.max_resources,
            # thread gauges currently compiled away → their 0s are elision,
            # not idleness (flips live with THREAD-rule loads)
            "threadsElided": bool(getattr(s, "threads_elided", False)),
        }
        info.update(extra_info or {})
        return CommandResponse.of_success(json.dumps(info))

    # ---- rules -----------------------------------------------------------

    _GET = {"flow": s.get_flow_rules, "degrade": s.get_degrade_rules,
            "system": s.get_system_rules, "authority": s.get_authority_rules,
            "paramFlow": s.get_param_flow_rules}
    _LOAD = {"flow": s.load_flow_rules, "degrade": s.load_degrade_rules,
             "system": s.load_system_rules,
             "authority": s.load_authority_rules,
             "paramFlow": s.load_param_flow_rules}

    def cmd_get_rules(req: CommandRequest) -> CommandResponse:
        rtype = req.param("type")
        getter = _GET.get(rtype)
        if getter is None:
            return CommandResponse.of_failure("invalid type", 400)
        return CommandResponse.of_success(codec.rules_to_json(rtype, getter()))

    def cmd_set_rules(req: CommandRequest) -> CommandResponse:
        rtype = req.param("type")
        loader = _LOAD.get(rtype)
        if loader is None:
            return CommandResponse.of_failure("invalid type", 400)
        try:
            data = req.param("data")
            if not data and req.body:
                data = req.body.decode("utf-8")   # UnicodeDecodeError ⊂ ValueError
            rules = codec.rules_from_json(rtype, data or "[]")
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(f"decode rules error: {exc}", 400)
        loader(rules)
        # ModifyRulesCommandHandler persists through the registered writable
        # datasource after a successful in-memory load; a failed write does
        # not undo the live rules, so still report success
        try:
            wreg.write_if_registered(rtype, rules)
        except OSError as exc:
            record_log().warning("setRules: datasource write failed: %s", exc)
        return CommandResponse.of_success("success")

    def cmd_get_param_rules(req: CommandRequest) -> CommandResponse:
        """Dedicated ``getParamFlowRules`` path — the reference DASHBOARD
        fetches param rules through this name, not ``getRules?type=``
        (``SentinelApiClient.java:105``)."""
        return CommandResponse.of_success(
            codec.rules_to_json("paramFlow", s.get_param_flow_rules()))

    def cmd_set_param_rules(req: CommandRequest) -> CommandResponse:
        req.parameters["type"] = "paramFlow"
        return cmd_set_rules(req)

    # ---- switch ----------------------------------------------------------

    def cmd_get_switch(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(
            f"Sentinel switch value: {'true' if s._global_on else 'false'}")

    def cmd_set_switch(req: CommandRequest) -> CommandResponse:
        value = req.param("value").lower()
        if value not in ("true", "false"):
            return CommandResponse.of_failure("invalid parameter", 400)
        s.set_global_switch(value == "true")
        return CommandResponse.of_success("success")

    # ---- metrics ---------------------------------------------------------

    def cmd_metric(req: CommandRequest) -> CommandResponse:
        if metric_searcher is None:
            return CommandResponse.of_success("")
        try:
            begin = int(req.param("startTime", "0") or 0)
        except ValueError:
            begin = 0
        end_raw = req.param("endTime", "")
        end = int(end_raw) if end_raw.isdigit() else None
        identity = req.param("identity", "")
        nodes = metric_searcher.find(begin, end, identity=identity or None,
                                     max_lines=_MAX_METRIC_LINES)
        if not identity:
            # SendMetricCommandHandler hides the global inbound node unless
            # asked for by name
            nodes = [n for n in nodes if n.resource != TOTAL_IN_RESOURCE_NAME]
        body = "".join(n.to_thin_string() + "\n" for n in nodes)
        if getattr(s, "threads_elided", False) and body:
            # marker line, not a metric line: dashboard clients skip lines
            # that don't parse as MetricNode (dashboard/client.py), and
            # elision-aware readers learn the 0 thread columns are elided
            body = "# threadsElided=true\n" + body
        return CommandResponse.of_success(body)

    # ---- node tree -------------------------------------------------------

    def _node_dicts():
        out = []
        rtypes = dict(getattr(s, "resource_types", {}) or {})
        elided = bool(getattr(s, "threads_elided", False))
        for name, row, t in s.all_node_totals():
            if not (t["pass"] or t["block"] or t["success"] or t["threads"]):
                continue
            out.append({
                "threadsElided": elided,
                "id": row,
                "resource": TOTAL_IN_RESOURCE_NAME if row == ENTRY_NODE_ROW
                else name,
                # ResourceTypeConstants classification (0 common, 1 web,
                # 2 rpc, 3 gateway) — the SPA's gateway tree grouping keys
                # off this the way the reference gateway identity page does
                "classification": int(rtypes.get(name, 0)),
                "threadNum": t["threads"], "passQps": t["pass"],
                "blockQps": t["block"], "totalQps": t["pass"] + t["block"],
                "successQps": t["success"], "exceptionQps": t["exception"],
                "averageRt": round(t["avg_rt"], 2),
            })
        return out

    def cmd_cluster_node(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(json.dumps(_node_dicts()))

    def cmd_cluster_node_by_id(req: CommandRequest) -> CommandResponse:
        rid = req.param("id")
        nodes = [n for n in _node_dicts() if n["resource"] == rid]
        return CommandResponse.of_success(json.dumps(nodes))

    def cmd_origin(req: CommandRequest) -> CommandResponse:
        rid = req.param("id")
        if not rid:
            return CommandResponse.of_failure("invalid parameter: id", 400)
        return CommandResponse.of_success(json.dumps(s.origin_totals(rid)))

    def cmd_tree(req: CommandRequest) -> CommandResponse:
        lines = ["EntranceNode: machine-root"]
        for n in _node_dicts():
            lines.append(
                f"-{n['resource']}({n['threadNum']}/{n['totalQps']}/"
                f"{n['passQps']}/{n['blockQps']}/{n['successQps']}/"
                f"{n['averageRt']})")
        return CommandResponse.of_success("\n".join(lines) + "\n")

    def cmd_json_tree(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(json.dumps(_node_dicts()))

    # ---- system ----------------------------------------------------------

    def cmd_system_status(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(json.dumps(s.system_status()))

    # ---- self-telemetry (obs/ — docs/OBSERVABILITY.md) -------------------

    def cmd_obs(req: CommandRequest) -> CommandResponse:
        """Runtime self-telemetry snapshot: decision counters, latency
        histograms (p50/p95/p99), recent sampled spans and block events.
        Params: ``spans`` (max spans, default 128), ``events`` (max block
        events, default 64), ``trace`` (a trace id → that trace's full
        span chain under ``"trace"``)."""
        obs = getattr(s, "obs", None)
        if obs is None:
            return CommandResponse.of_failure("observability unavailable",
                                              404)
        try:
            span_limit = int(req.param("spans", "128") or 128)
            event_limit = int(req.param("events", "64") or 64)
        except ValueError:
            return CommandResponse.of_failure("invalid limit", 400)
        payload = obs.payload(span_limit=span_limit,
                              event_limit=event_limit)
        payload["threadsElided"] = s.threads_elided
        trace = req.param("trace", "")
        if trace:
            try:
                payload["trace"] = obs.spans.chain(int(trace))
            except ValueError:
                return CommandResponse.of_failure("invalid trace id", 400)
        return CommandResponse.of_success(json.dumps(payload))

    def cmd_topk(req: CommandRequest) -> CommandResponse:
        """Hot-resource telemetry snapshot (obs/telemetry.py): the last
        drained device top-K (per-resource rolling pass/block/qps, plus
        ``rt_p50_ms``/``rt_p95_ms``/``rt_p99_ms`` and the raw
        ``rt_hist`` bucket vector when the device-resident RT histogram
        table is enabled — obs/resource_hist.py) plus the engine-wide
        per-second timeline tail. Params: ``timeline`` (max timeline
        entries, default 60), ``tick`` (``1`` → run one poll inline
        first — the pull-only path for agents without the telemetry
        ticker running)."""
        telemetry = getattr(s, "telemetry", None)
        if telemetry is None:
            return CommandResponse.of_failure("telemetry unavailable", 404)
        try:
            timeline_limit = int(req.param("timeline", "60") or 60)
        except ValueError:
            return CommandResponse.of_failure("invalid limit", 400)
        if req.param("tick", "") in ("1", "true"):
            telemetry.poll()
        return CommandResponse.of_success(json.dumps(
            telemetry.snapshot(timeline_limit=timeline_limit)))

    def cmd_control(req: CommandRequest) -> CommandResponse:
        """Overload-controller snapshot (control/loop.py): policy state
        (admission fraction, estimator extrema, degrade trackers), the
        last observation, and the applied-action tail with per-action
        evidence. Params: ``actions`` (max actions, default 32),
        ``tick`` (``1`` → run one observe/decide/apply cycle inline
        first — the pull-only path without a scheduler)."""
        control = getattr(s, "control", None)
        if control is None:
            return CommandResponse.of_failure("controller unavailable", 404)
        try:
            limit = int(req.param("actions", "32") or 32)
        except ValueError:
            return CommandResponse.of_failure("invalid limit", 400)
        if req.param("tick", "") in ("1", "true"):
            control.poll()
        return CommandResponse.of_success(json.dumps(
            control.snapshot(limit=limit)))

    def cmd_trace(req: CommandRequest) -> CommandResponse:
        """Request-scoped trace export (docs/OBSERVABILITY.md "Request
        tracing"). Params: ``id`` (a trace id → that chain's causal
        closure as a Chrome-trace-event/Perfetto document; when the
        flight recorder pinned the id, the pinned — possibly
        richer-than-ring — record is exported); without ``id``, the
        pinned-record index (``{"pinned": [...metadata...]}``)."""
        obs = getattr(s, "obs", None)
        if obs is None:
            return CommandResponse.of_failure("observability unavailable",
                                              404)
        from sentinel_tpu.obs import traceexport
        raw = req.param("id", "")
        if not raw:
            return CommandResponse.of_success(json.dumps({
                "pinned": obs.flight.snapshot(limit=32)}))
        try:
            trace_id = int(raw)
        except ValueError:
            return CommandResponse.of_failure("invalid trace id", 400)
        pinned = obs.flight.pinned(trace_id)
        doc = (traceexport.chrome_trace(pinned) if pinned is not None
               else traceexport.export_chain(obs.spans, trace_id))
        return CommandResponse.of_success(json.dumps(doc))

    # ---- cluster mode ----------------------------------------------------

    def cmd_get_cluster_mode(req: CommandRequest) -> CommandResponse:
        info = {
            "mode": cstate.mode,
            "lastModified": cstate.last_modified_ms,
            "clientAvailable": True, "serverAvailable": True,
        }
        if cstate.info_provider is not None:
            try:
                info.update(cstate.info_provider() or {})
            except Exception:
                pass
        return CommandResponse.of_success(json.dumps(info))

    def cmd_get_cluster_client_config(req: CommandRequest) -> CommandResponse:
        return CommandResponse.of_success(json.dumps(cstate.client_config))

    def cmd_set_cluster_client_config(req: CommandRequest) -> CommandResponse:
        """``cluster/client/modifyConfig`` analog: point the token client at
        a (new) server; a running client reconnects via the observers."""
        data = req.param("data")
        if not data and req.body:
            try:
                data = req.body.decode("utf-8")
            except UnicodeDecodeError:
                return CommandResponse.of_failure("invalid body", 400)
        try:
            cfg_in = json.loads(data or "{}")
            cfg_out = {"serverHost": str(cfg_in["serverHost"]),
                       "serverPort": int(cfg_in["serverPort"])}
            if "requestTimeout" in cfg_in:
                cfg_out["requestTimeout"] = int(cfg_in["requestTimeout"])
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(f"invalid config: {exc}", 400)
        cstate.set_client_config(cfg_out)
        return CommandResponse.of_success("success")

    def cmd_set_cluster_mode(req: CommandRequest) -> CommandResponse:
        try:
            mode = int(req.param("mode"))
        except ValueError:
            return CommandResponse.of_failure("invalid mode", 400)
        if mode not in (CLUSTER_NOT_STARTED, CLUSTER_CLIENT, CLUSTER_SERVER):
            return CommandResponse.of_failure("invalid mode", 400)
        cstate.set_mode(mode, s.clock.now_ms())
        return CommandResponse.of_success("success")

    # ---- gateway (sentinel-api-gateway-adapter-common command handlers,
    # registered only when the app wired up the gateway managers) --------

    def _body_or_data(req: CommandRequest) -> str:
        data = req.param("data")
        if not data and req.body:
            data = req.body.decode("utf-8")    # UnicodeDecodeError ⊂ ValueError
        return data or "[]"

    def cmd_gateway_get_rules(req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.gateway.codec import gateway_rules_to_json
        return CommandResponse.of_success(
            gateway_rules_to_json(gateway_manager.all_rules()))

    def cmd_gateway_update_rules(req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.gateway.codec import gateway_rules_from_json
        try:
            rules = gateway_rules_from_json(_body_or_data(req))
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(f"decode rules error: {exc}", 400)
        gateway_manager.load_rules(rules)
        return CommandResponse.of_success("success")

    def cmd_gateway_get_apis(req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.gateway.codec import api_definitions_to_json
        return CommandResponse.of_success(api_definitions_to_json(
            api_definition_manager.get_api_definitions()))

    def cmd_gateway_update_apis(req: CommandRequest) -> CommandResponse:
        from sentinel_tpu.gateway.codec import api_definitions_from_json
        try:
            defs = api_definitions_from_json(_body_or_data(req))
        except (ValueError, KeyError, TypeError) as exc:
            return CommandResponse.of_failure(f"decode apis error: {exc}", 400)
        api_definition_manager.load_api_definitions(defs)
        return CommandResponse.of_success("success")

    if gateway_manager is not None:
        center.register(cmd_gateway_get_rules, "gateway/getRules",
                        "get gateway flow rules")
        center.register(cmd_gateway_update_rules, "gateway/updateRules",
                        "set gateway flow rules")
    if api_definition_manager is not None:
        center.register(cmd_gateway_get_apis, "gateway/getApiDefinitions",
                        "get gateway api groups")
        center.register(cmd_gateway_update_apis,
                        "gateway/updateApiDefinitions",
                        "set gateway api groups")

    for name, desc, fn in [
        ("version", "get sentinel version", cmd_version),
        ("api", "list available commands", cmd_api),
        ("basicInfo", "get app basic info", cmd_basic_info),
        ("getRules", "get rules by type", cmd_get_rules),
        ("setRules", "load rules by type", cmd_set_rules),
        ("getSwitch", "get global switch", cmd_get_switch),
        ("setSwitch", "set global switch", cmd_set_switch),
        ("metric", "search metric logs", cmd_metric),
        ("clusterNode", "all resource nodes", cmd_cluster_node),
        ("clusterNodeById", "resource node by name", cmd_cluster_node_by_id),
        ("cnode", "resource node by name", cmd_cluster_node_by_id),
        ("origin", "per-origin stats of a resource", cmd_origin),
        ("tree", "node tree (text)", cmd_tree),
        ("jsonTree", "node tree (json)", cmd_json_tree),
        ("systemStatus", "system adaptive status", cmd_system_status),
        ("obs", "runtime self-telemetry snapshot", cmd_obs),
        ("topk", "hot-resource top-K snapshot", cmd_topk),
        ("control", "overload controller snapshot", cmd_control),
        ("trace", "causal trace chain as chrome-trace JSON", cmd_trace),
        ("getClusterMode", "get cluster mode", cmd_get_cluster_mode),
        ("setClusterMode", "set cluster mode", cmd_set_cluster_mode),
        ("getClusterClientConfig", "get cluster client config",
         cmd_get_cluster_client_config),
        ("setClusterClientConfig", "point the token client at a server",
         cmd_set_cluster_client_config),
        # reference-dashboard exact paths (SentinelApiClient.java:105-111):
        # param rules use dedicated commands, client config the cluster/
        # client/* names — aliases so a REAL Sentinel dashboard can drive
        # this agent unchanged
        ("getParamFlowRules", "get param flow rules", cmd_get_param_rules),
        ("setParamFlowRules", "set param flow rules", cmd_set_param_rules),
        ("cluster/client/fetchConfig", "get cluster client config",
         cmd_get_cluster_client_config),
        ("cluster/client/modifyConfig", "modify cluster client config",
         cmd_set_cluster_client_config),
    ]:
        center.register(fn, name, desc)

    # SPI-discovered custom command handlers (CommandHandler SPI analog —
    # providers carry command_name/command_desc; see core/spi.py and
    # demos/command_handler_spi.py)
    from sentinel_tpu.core.spi import SERVICE_COMMAND_HANDLER, SpiLoader
    for handler in SpiLoader.of(
            SERVICE_COMMAND_HANDLER).load_instance_list_sorted():
        center.register(handler)

    return cstate
