"""HTTP transport for the ingest tier: POST an entry, get a verdict.

Unlike adapters/aiohttp_server.py — which guards an application's OWN
aiohttp handlers via middleware — this module exposes the decision
engine itself as a service: a sidecar / central flow-control endpoint
that remote callers consult before doing work. Handlers are thin
wrappers over :meth:`AdaptiveBatcher.submit`, so every HTTP request
rides the same deadline-driven batching as in-process callers.

Routes (``make_app``):

* ``POST /v1/entry`` — body ``{"resource": str, "count"?: int,
  "prioritized"?: bool, "origin"?: str, "deadline_ms"?: int}`` →
  ``200 {"allow", "reason", "reason_name", "wait_ms", "latency_ms"}``;
  ``429`` when blocked is NOT used — blocks are verdicts, not errors —
  but backpressure shed and shutdown map to ``503``.
* ``POST /v1/entry_batch`` — body ``{"entries": [entry, ...]}`` →
  ``200 {"verdicts": [verdict-or-{"error": ...}, ...]}`` (positional).
* ``GET /healthz`` — liveness + pending depth.
* ``GET /stats`` — frontend counters + request-latency histogram
  snapshot (the full payload stays on the dashboard/transport tier).

Usage::

    batcher = sph.frontend()
    runner = await start_server(batcher, host="0.0.0.0", port=8719)
    ...
    await runner.cleanup()
"""

from __future__ import annotations

import asyncio
from typing import Optional

from aiohttp import web

from sentinel_tpu.frontend.batcher import (
    AdaptiveBatcher, FrontendClosed, IngestOverload, RequestVerdict,
)

DEFAULT_PORT = 8719


def _verdict_json(v: RequestVerdict) -> dict:
    return {
        "allow": v.allow,
        "reason": v.reason,
        "reason_name": v.reason_name,
        "wait_ms": v.wait_ms,
        "latency_ms": round(v.latency_ms, 3),
        "trace_id": v.trace_id,
    }


def _parse_entry(body: dict) -> dict:
    resource = body.get("resource")
    if not isinstance(resource, str) or not resource:
        raise web.HTTPBadRequest(text="missing or non-string 'resource'")
    kwargs = {"resource": resource}
    if "count" in body:
        kwargs["count"] = int(body["count"])
    if "prioritized" in body:
        kwargs["prioritized"] = bool(body["prioritized"])
    if "origin" in body:
        kwargs["origin"] = str(body["origin"])
    if "deadline_ms" in body:
        kwargs["deadline_ms"] = int(body["deadline_ms"])
    return kwargs


async def _submit_one(batcher: AdaptiveBatcher, kwargs: dict):
    resource = kwargs.pop("resource")
    return await batcher.submit(resource, **kwargs)


def make_app(batcher: AdaptiveBatcher) -> web.Application:
    """The ingest endpoint as an aiohttp app (mountable as a subapp)."""

    async def entry(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            kwargs = _parse_entry(body if isinstance(body, dict) else {})
        except web.HTTPBadRequest:
            raise
        except Exception:
            raise web.HTTPBadRequest(text="body must be a JSON object")
        try:
            verdict = await _submit_one(batcher, kwargs)
        except (IngestOverload, FrontendClosed) as exc:
            raise web.HTTPServiceUnavailable(text=str(exc))
        return web.json_response(_verdict_json(verdict))

    async def entry_batch(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            entries = body.get("entries") if isinstance(body, dict) else None
            if not isinstance(entries, list):
                raise ValueError
            parsed = [_parse_entry(e if isinstance(e, dict) else {})
                      for e in entries]
        except web.HTTPBadRequest:
            raise
        except Exception:
            raise web.HTTPBadRequest(
                text="body must be {\"entries\": [...]}")
        results = await asyncio.gather(
            *(_submit_one(batcher, k) for k in parsed),
            return_exceptions=True)
        out = []
        for r in results:
            if isinstance(r, RequestVerdict):
                out.append(_verdict_json(r))
            elif isinstance(r, (IngestOverload, FrontendClosed)):
                out.append({"error": type(r).__name__, "detail": str(r)})
            elif isinstance(r, BaseException):
                raise r
        return web.json_response({"verdicts": out})

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"ok": not batcher._closed,
                                  "pending": batcher.pending})

    async def stats(request: web.Request) -> web.Response:
        obs = batcher._s.obs
        counters = {k: v for k, v in obs.counters.snapshot().items()
                    if k.startswith("frontend.") or k.startswith("pipeline.")}
        return web.json_response({
            "counters": counters,
            "hist_request_to_verdict": obs.hist_request.snapshot(),
            "pending": batcher.pending,
        })

    app = web.Application()
    app.add_routes([
        web.post("/v1/entry", entry),
        web.post("/v1/entry_batch", entry_batch),
        web.get("/healthz", healthz),
        web.get("/stats", stats),
    ])
    return app


async def start_server(batcher: AdaptiveBatcher, host: str = "127.0.0.1",
                       port: int = DEFAULT_PORT,
                       app: Optional[web.Application] = None):
    """Bind and serve; returns the ``AppRunner`` (``await
    runner.cleanup()`` to stop). The batcher itself stops via
    ``Sentinel.close()`` / ``batcher.close()``."""
    runner = web.AppRunner(app if app is not None else make_app(batcher))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
