"""Deadline-driven adaptive batching: the async ingest tier.

Every bench before round 7 drove the runtime with pre-formed uniform
batches, so "24M decisions/s" had no request→verdict latency attached.
This module is the tier a real Sentinel deployment puts above the
dispatch pipeline: individual requests (resource, count, priority,
deadline) arrive on an asyncio loop, coalesce into device batches, and
dispatch at **min(B_max, oldest-deadline)** — a batch is cut the moment
it fills, OR the moment the head-of-queue request's latency budget is
about to expire, OR when the arrival stream goes idle (waiting longer
would buy no coalescing, only latency). Verdicts fan back out to
per-request futures in dispatch order, bit-identical to a sequential
``entry_batch`` loop over the same stream (tests/test_frontend.py).

Two layers, split so the deadline policy is testable under the virtual
clock without an event loop:

* :class:`IngestQueue` — the pure policy core: holds pending requests,
  answers "should this batch flush NOW, and why" (``flush_reason``) and
  "when must the loop wake next" (``fire_at_ms``). No asyncio, no
  engine; driven by explicit ``now_ms`` values.
* :class:`AdaptiveBatcher` — the asyncio overlay: an ingest loop that
  waits on ``min(time-to-deadline, idle-gap)``, a dispatch step that
  rides :class:`~sentinel_tpu.serving.DispatchPipeline` (depth-k
  in-flight window, in-order settle), and a settle loop that fans
  verdicts back to futures. Engine round-trips (``.result()``
  readbacks) run in ``asyncio.to_thread`` so the event loop never
  blocks on the device; a depth-semaphore released from the pipeline's
  ``on_settle`` hook keeps at most ``depth`` batches in flight without
  ever letting ``submit`` stall inside the loop thread.

Host prep stays on the PR 4 fast path: resource names intern ONCE into
an instance row cache (``Sentinel.intern_resources`` semantics) and
flushes dispatch pre-interned int32 row arrays.

Backpressure: at most ``queue_max`` requests may be pending + in
flight; past that ``submit`` raises :class:`IngestOverload` immediately
(fail-fast shed — the caller sees 503, not an unbounded queue) and the
``frontend.shed`` counter ticks.

Shutdown: the batcher registers with ``Sentinel.register_shutdown``, so
``Sentinel.close()`` tears it down — pending futures fail with
:class:`FrontendClosed` (never silently leak), already-dispatched
device work settles through ``DispatchPipeline.flush()`` so engine
bookkeeping stays consistent.

Env knobs (read at construction; constructor kwargs override):

* ``SENTINEL_FRONTEND_BATCH`` — B_max, default 256;
* ``SENTINEL_FRONTEND_DEADLINE_MS`` — default per-request budget, 25;
* ``SENTINEL_FRONTEND_BUDGET_MS`` — dispatch+device reserve subtracted
  from each deadline when computing the fire point, default 3;
* ``SENTINEL_FRONTEND_IDLE_MS`` — arrival gap after which a partial
  batch flushes early, default 1.0 (0 = flush whenever ingest drains);
* ``SENTINEL_FRONTEND_QUEUE`` — backpressure bound, default 8·B_max.

Self-telemetry (obs/): counters ``frontend.enqueue``,
``frontend.queue_depth`` (sum of pending depth at each enqueue),
``frontend.shed``, ``frontend.flush_reason.{full,deadline,idle}``;
spans ``frontend.enqueue`` / ``frontend.flush`` on sampled requests and
flushes; per-request ingest→verdict ns in ``obs.hist_request`` (the
p50/p95/p99 a service owner quotes).

Request-scoped tracing (PR 8, docs/OBSERVABILITY.md "Request tracing"):
``submit`` mints a per-request trace id (every request while the flight
recorder is active, stride-sampled otherwise), the flush records fan-in
links request→batch and threads the batch trace through
``DispatchPipeline.submit(trace_id=...)`` into the device spans, and the
settle loop records fan-out links batch→request plus the terminal
``frontend.settle`` span — so ``obs.spans.chain(request_id)`` walks the
full lifecycle. SLO triggers fired from here: ``shed`` on
:class:`IngestOverload`, ``deadline_miss`` on the worst overrun of each
settled batch, and the rolling p99 check (obs/flight.py).
"""

from __future__ import annotations

import asyncio
import collections
import os
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from sentinel_tpu.core import errors as err_mod
from sentinel_tpu.obs import counters as obs_keys
from sentinel_tpu.serving import DispatchPipeline

FRONTEND_BATCH_ENV = "SENTINEL_FRONTEND_BATCH"
FRONTEND_DEADLINE_ENV = "SENTINEL_FRONTEND_DEADLINE_MS"
FRONTEND_BUDGET_ENV = "SENTINEL_FRONTEND_BUDGET_MS"
FRONTEND_IDLE_ENV = "SENTINEL_FRONTEND_IDLE_MS"
FRONTEND_QUEUE_ENV = "SENTINEL_FRONTEND_QUEUE"

FLUSH_FULL = "full"
FLUSH_DEADLINE = "deadline"
FLUSH_IDLE = "idle"

_FLUSH_KEY = {
    FLUSH_FULL: obs_keys.FE_FLUSH_FULL,
    FLUSH_DEADLINE: obs_keys.FE_FLUSH_DEADLINE,
    FLUSH_IDLE: obs_keys.FE_FLUSH_IDLE,
}


def _env_num(name: str, default, lo, hi, cast=int):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return min(hi, max(lo, cast(raw)))
    except ValueError:
        return default


def frontend_batch_max(default: int = 256) -> int:
    """``SENTINEL_FRONTEND_BATCH``, clamped to [1, 65536]."""
    return _env_num(FRONTEND_BATCH_ENV, default, 1, 1 << 16)


def frontend_deadline_ms(default: int = 25) -> int:
    """``SENTINEL_FRONTEND_DEADLINE_MS``, clamped to [1, 60000]."""
    return _env_num(FRONTEND_DEADLINE_ENV, default, 1, 60_000)


def frontend_budget_ms(default: int = 3) -> int:
    """``SENTINEL_FRONTEND_BUDGET_MS``, clamped to [0, 10000]."""
    return _env_num(FRONTEND_BUDGET_ENV, default, 0, 10_000)


def frontend_idle_ms(default: float = 1.0) -> float:
    """``SENTINEL_FRONTEND_IDLE_MS``, clamped to [0, 1000]."""
    return _env_num(FRONTEND_IDLE_ENV, default, 0.0, 1000.0, cast=float)


def frontend_queue_max(batch_max: int) -> int:
    """``SENTINEL_FRONTEND_QUEUE``, default 8·B_max, clamped ≥ B_max."""
    return _env_num(FRONTEND_QUEUE_ENV, 8 * batch_max, batch_max, 1 << 22)


class IngestOverload(RuntimeError):
    """Backpressure shed: the ingest queue is at ``queue_max`` — the
    request was rejected WITHOUT being enqueued (map to HTTP 503)."""


class FrontendClosed(RuntimeError):
    """The batcher (or its Sentinel) was closed while this request was
    still pending; no verdict was produced."""


class RequestVerdict(NamedTuple):
    """Per-request verdict fanned out of a batch decision."""

    allow: bool
    reason: int          # int8 verdict code (0 = pass)
    wait_ms: int         # PriorityWait / pacing hint
    latency_ms: float    # ingest → verdict, this request
    trace_id: int = 0    # request-scoped trace id (0 = not traced)

    @property
    def reason_name(self) -> str:
        return "" if self.allow else err_mod.exception_name_for(self.reason)


class _Pending:
    __slots__ = ("resource", "count", "prioritized", "origin",
                 "deadline_ms", "t0_ns", "future", "trace_id")

    def __init__(self, resource, count, prioritized, origin, deadline_ms,
                 t0_ns, future, trace_id=0):
        self.resource = resource
        self.count = count
        self.prioritized = prioritized
        self.origin = origin
        self.deadline_ms = deadline_ms      # ABSOLUTE fire-by time
        self.t0_ns = t0_ns
        self.future = future
        self.trace_id = trace_id            # request-scoped trace (0=off)


class IngestQueue:
    """The pure flush policy: dispatch at ``min(B_max, oldest-deadline)``.

    Holds pending requests FIFO and answers, for an explicit ``now_ms``:

    * :meth:`flush_reason` — ``"full"`` when ≥ ``batch_max`` requests
      are pending; ``"deadline"`` when the oldest pending deadline
      (minus the ``budget_ms`` dispatch+device reserve) has arrived;
      ``"idle"`` when the caller reports the arrival stream went idle
      (no new request within ``idle_ms``) and anything is pending;
      ``None`` otherwise (keep coalescing).
    * :meth:`fire_at_ms` — the absolute time the deadline rule will
      trigger (the loop's next wake-up bound).

    No asyncio, no engine — tests drive it directly under the virtual
    clock (tests/test_frontend.py)."""

    def __init__(self, batch_max: int, budget_ms: int = 0,
                 queue_max: Optional[int] = None):
        self.batch_max = max(1, int(batch_max))
        self.budget_ms = max(0, int(budget_ms))
        self.queue_max = (self.batch_max * 8 if queue_max is None
                          else max(1, int(queue_max)))
        self._q: "collections.deque[_Pending]" = collections.deque()
        self._min_deadline: Optional[int] = None
        # controller-settable admission gate (round 17): fraction of
        # arriving requests admitted BEFORE they join a batch. 1.0 = the
        # gate is wide open and admitted() takes the zero-state early
        # return, so an idle controller leaves the request stream (and
        # every downstream verdict) bit-identical to pre-r17.
        self.admit_frac = 1.0
        self.admit_seed = 0
        self._admit_idx = 0

    def set_admission(self, frac: float, seed: int = 0) -> None:
        """Controller actuation: admit only ``frac`` of arriving
        requests. Deterministic — the drop pattern is a pure function of
        ``(seed, arrival index, resource)``, so a replay of the same
        request stream with the same seed sheds the same requests (the
        property the gate's replayability check rides on)."""
        self.admit_frac = min(1.0, max(0.0, float(frac)))
        self.admit_seed = int(seed) & 0xFFFFFFFF
        self._admit_idx = 0

    def admitted(self, resource: str) -> bool:
        """One admission draw (consumes one arrival index when the gate
        is engaged; free when wide open)."""
        if self.admit_frac >= 1.0:
            return True
        idx = self._admit_idx
        self._admit_idx = idx + 1
        mix = (self.admit_seed * 0x9E3779B1 + idx) & 0xFFFFFFFF
        h = zlib.crc32(resource.encode("utf-8", "replace"), mix)
        return (h & 0xFFFFFF) / float(1 << 24) < self.admit_frac

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.batch_max

    def would_shed(self, inflight: int = 0) -> bool:
        return len(self._q) + inflight >= self.queue_max

    def add(self, req: _Pending) -> None:
        self._q.append(req)
        if self._min_deadline is None or req.deadline_ms < self._min_deadline:
            self._min_deadline = req.deadline_ms

    def fire_at_ms(self) -> Optional[int]:
        """Absolute ms at which the deadline rule fires (oldest pending
        deadline minus the dispatch budget); None when empty."""
        if self._min_deadline is None:
            return None
        return self._min_deadline - self.budget_ms

    def flush_reason(self, now_ms: int, idle: bool = False) -> Optional[str]:
        if not self._q:
            return None
        if len(self._q) >= self.batch_max:
            return FLUSH_FULL
        fire = self.fire_at_ms()
        if fire is not None and now_ms >= fire:
            return FLUSH_DEADLINE
        if idle:
            return FLUSH_IDLE
        return None

    def take(self) -> List[_Pending]:
        """Pop up to ``batch_max`` requests in arrival order."""
        n = min(len(self._q), self.batch_max)
        out = [self._q.popleft() for _ in range(n)]
        self._min_deadline = (min(r.deadline_ms for r in self._q)
                              if self._q else None)
        return out

    def take_all(self) -> List[_Pending]:
        out = list(self._q)
        self._q.clear()
        self._min_deadline = None
        return out


class AdaptiveBatcher:
    """Asyncio ingest front end over one :class:`Sentinel`.

    In-process async client API (also what frontend/server.py's HTTP
    handlers call)::

        batcher = sph.frontend()            # or AdaptiveBatcher(sph)
        verdict = await batcher.submit("api", count=1, origin="app-a")
        if verdict.allow: ...

    One batcher per event loop; the ingest/settle tasks start lazily on
    the loop of the first ``submit`` and die with ``close()``. All
    engine round-trips run in worker threads (``asyncio.to_thread``) —
    the loop thread never blocks on a device readback."""

    def __init__(self, sentinel, *, batch_max: Optional[int] = None,
                 deadline_ms: Optional[int] = None,
                 budget_ms: Optional[int] = None,
                 idle_ms: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 depth: Optional[int] = None,
                 record_flushes: bool = False):
        self._s = sentinel
        self.batch_max = (frontend_batch_max() if batch_max is None
                          else max(1, int(batch_max)))
        self.deadline_ms = (frontend_deadline_ms() if deadline_ms is None
                            else max(1, int(deadline_ms)))
        self.budget_ms = (frontend_budget_ms() if budget_ms is None
                          else max(0, int(budget_ms)))
        self.idle_ms = (frontend_idle_ms() if idle_ms is None
                        else max(0.0, float(idle_ms)))
        self.queue = IngestQueue(
            self.batch_max, self.budget_ms,
            frontend_queue_max(self.batch_max) if queue_max is None
            else queue_max)
        self._pipe = DispatchPipeline(sentinel, depth=depth,
                                      on_settle=self._pipe_settled)
        self.depth = self._pipe.depth
        # name → pre-interned row (PR 4 host-prep fast path); grows to at
        # most the resource universe, same staleness class as any
        # name→row cache (see entry_batch_nowait docstring). Round 15:
        # demotions prune their entries so the cache is bounded by the
        # hot tier, not the (now unbounded) key universe, and a demoted
        # key's next request re-interns — the promotion trigger.
        self._rows: Dict[str, int] = {}
        tiering = getattr(sentinel, "tiering", None)
        if tiering is not None and tiering.enabled:
            tiering.add_demote_listener(self._on_demoted)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self._slots: Optional[asyncio.Semaphore] = None
        self._settle_q: Optional[asyncio.Queue] = None
        self._run_task = None
        self._settle_task = None
        self._inflight = 0              # requests dispatched, not settled
        self._inflight_reqs: "collections.deque" = collections.deque()
        self._closed = False
        self._close_lock = threading.Lock()
        self.flush_log: List[dict] = [] if record_flushes else None
        reg = getattr(sentinel, "register_shutdown", None)
        if reg is not None:
            reg(self)

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------

    async def submit(self, resource: str, *, count: int = 1,
                     prioritized: bool = False, origin: str = "",
                     deadline_ms: Optional[int] = None) -> RequestVerdict:
        """Enqueue one request; resolves when its batch's verdicts land.

        ``deadline_ms`` is this request's latency budget RELATIVE to now
        (default ``SENTINEL_FRONTEND_DEADLINE_MS``); the batch it joins
        dispatches no later than ``deadline - budget_ms``. Raises
        :class:`IngestOverload` at the backpressure bound and
        :class:`FrontendClosed` after shutdown."""
        if self._closed:
            raise FrontendClosed("ingest front end is closed")
        self._ensure_started()
        obs = self._s.obs
        obs_on = obs.enabled
        # request-scoped trace id: the flight recorder's always-on tier
        # mints for EVERY request (an SLO trigger must be able to pin any
        # chain retroactively); without it the stride sampler decides
        tr = obs.request_trace() if obs_on else 0
        t0 = obs.spans.now_ns() if obs_on else 0
        if not self.queue.admitted(resource):
            # controller shed: dropped BEFORE the batch forms, so the
            # device never sees the request (the whole point — overload
            # relief must not cost a dispatch). The triggering action
            # already pinned a flight chain; per-request drops only count.
            if obs_on:
                obs.counters.add(obs_keys.CONTROL_DROPPED)
                obs.counters.add(obs_keys.FE_SHED)
            raise IngestOverload(
                f"admission controller shedding "
                f"(frac={self.queue.admit_frac:.3f}); request shed")
        if self.queue.would_shed(self._inflight):
            if obs_on:
                obs.counters.add(obs_keys.FE_SHED)
                obs.flight.trigger("shed", note=f"resource={resource}")
            raise IngestOverload(
                f"ingest queue at bound ({self.queue.queue_max} pending"
                f"+inflight); request shed")
        now = self._s.clock.now_ms()
        budget = self.deadline_ms if deadline_ms is None else max(
            1, int(deadline_ms))
        req = _Pending(resource, int(count), bool(prioritized), origin,
                       now + budget, t0 if obs_on else 0,
                       self._loop.create_future(), tr)
        self.queue.add(req)
        if obs_on:
            obs.counters.add(obs_keys.FE_ENQUEUE)
            obs.counters.add(obs_keys.FE_QUEUE_DEPTH, len(self.queue))
            if tr:
                obs.spans.record(tr, "frontend.enqueue", t0,
                                 obs.spans.now_ns(),
                                 note=f"depth={len(self.queue)}")
        self._wake.set()
        return await req.future

    async def drain(self) -> None:
        """Flush everything pending (idle-reason batches) and wait until
        every dispatched batch has settled and fanned out."""
        self._ensure_started()
        while len(self.queue) or self._inflight:
            if len(self.queue):
                await self._flush(FLUSH_IDLE)
            else:
                await asyncio.sleep(0.001)

    @property
    def pending(self) -> int:
        """Requests accepted but not yet fanned out (queued + in flight)."""
        return len(self.queue) + self._inflight

    def retune(self, budget_ms: Optional[int] = None,
               batch_cap: Optional[int] = None) -> None:
        """Controller actuation: hot-swap the flush-deadline reserve and
        the batch cap ONLINE. Pure host-side policy state — no retrace,
        no new engine geometry (padded dispatch widths are chosen per
        flush, exactly as before). Callable from any thread; the ingest
        loop picks the new values up on its next wake. A ``batch_cap``
        above the construction-time ``batch_max`` is clamped: the
        controller may only trade throughput for latency, never exceed
        the operator's provisioned batch width."""
        if budget_ms is not None:
            self.budget_ms = max(0, int(budget_ms))
            self.queue.budget_ms = self.budget_ms
        if batch_cap is not None:
            cap = min(self.batch_max, max(1, int(batch_cap)))
            self.queue.batch_max = cap
        loop, wake = self._loop, self._wake
        if loop is not None and wake is not None and not loop.is_closed():
            loop.call_soon_threadsafe(wake.set)

    # ------------------------------------------------------------------
    # ingest loop
    # ------------------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._run_task is not None:
            return
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._wake = asyncio.Event()
        self._slots = asyncio.Semaphore(self.depth)
        self._flush_lock = asyncio.Lock()
        self._settle_q = asyncio.Queue()
        self._run_task = loop.create_task(self._run())
        self._settle_task = loop.create_task(self._settle_loop())

    async def _run(self) -> None:
        """The adaptive ingest loop: coalesce until full / deadline /
        idle, then flush. Waits are bounded by the EARLIER of the
        oldest pending deadline and the idle gap."""
        while not self._closed:
            if not len(self.queue):
                self._wake.clear()
                if not len(self.queue):        # re-check after clear
                    await self._wake.wait()
                continue
            now = self._s.clock.now_ms()
            reason = self.queue.flush_reason(now)
            if reason is None:
                fire = self.queue.fire_at_ms()
                # bounded by the EARLIER of deadline and idle gap; an
                # idle_ms of 0 flushes as soon as ingest drains (one
                # loop pass of coalescing, minimum latency)
                wait_ms = min(max(0.0, float(fire - now)), self.idle_ms)
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           wait_ms / 1000.0)
                    continue                    # new arrival: re-coalesce
                except asyncio.TimeoutError:
                    now = self._s.clock.now_ms()
                    reason = self.queue.flush_reason(now, idle=True)
                    if reason is None:          # raced an empty queue
                        continue
            await self._flush(reason)

    async def _flush(self, reason: str) -> None:
        # serialized: the ingest loop and drain() may both flush, and
        # pipeline submission order IS engine-state order — interleaved
        # dispatches would make batch order (hence QPS depletion order)
        # nondeterministic
        async with self._flush_lock:
            await self._flush_locked(reason)

    async def _flush_locked(self, reason: str) -> None:
        reqs = self.queue.take()
        if not reqs:
            return
        obs = self._s.obs
        obs_on = obs.enabled
        tr = obs.request_trace() if obs_on else 0
        t0 = obs.spans.now_ns() if tr else 0
        if obs_on:
            obs.counters.add(_FLUSH_KEY[reason])
        if tr:
            # fan-in: every request trace joins this batch's trace (the
            # causal edges chain(request_id) walks to reach the
            # pipeline/device spans)
            for r in reqs:
                if r.trace_id:
                    obs.spans.link(r.trace_id, tr, "flush")
        if self.flush_log is not None:
            self.flush_log.append({
                "reason": reason,
                "resources": [r.resource for r in reqs],
                "counts": [r.count for r in reqs],
                "prioritized": [r.prioritized for r in reqs],
                "origins": [r.origin for r in reqs],
            })
        self._inflight += len(reqs)
        # free pipeline slot BEFORE dispatching: the semaphore (released
        # from the pipeline's on_settle hook) bounds in-flight batches at
        # `depth` without DispatchPipeline.submit ever stalling — a stall
        # would block a worker thread on a device readback mid-dispatch
        await self._slots.acquire()
        ticket = await asyncio.to_thread(self._dispatch, reqs, tr)
        if tr:
            obs.spans.record(tr, "frontend.flush", t0, obs.spans.now_ns(),
                             n=len(reqs), note=reason)
        self._inflight_reqs.append(reqs)
        await self._settle_q.put((ticket, reqs, tr))

    def _dispatch(self, reqs: List[_Pending], trace_id: int = 0):
        """Host prep + device dispatch for one batch (worker thread).
        Rows are pre-interned through the instance cache; misses intern
        once via the vectorized registry path. ``trace_id`` (the batch
        trace) threads through the pipeline seq into the device spans."""
        n = len(reqs)
        rows = np.empty(n, np.int32)
        cache = self._rows
        miss_idx: List[int] = []
        for i, r in enumerate(reqs):
            row = cache.get(r.resource)
            if row is None:
                miss_idx.append(i)
            else:
                rows[i] = row
        if miss_idx:
            names = [reqs[i].resource for i in miss_idx]
            fresh = self._s.intern_resources(names)
            for i, row in zip(miss_idx, fresh):
                cache[reqs[i].resource] = int(row)
                rows[i] = row
        if n > len(miss_idx):
            # cache hits are resident by construction (demotion pruned);
            # count them so the tier hit rate covers the cached path too
            tiering = getattr(self._s, "tiering", None)
            if tiering is not None:
                tiering.note_hot_hits(n - len(miss_idx))
        acquire = np.fromiter((r.count for r in reqs), np.int32, count=n)
        prio = np.fromiter((r.prioritized for r in reqs), np.bool_, count=n)
        origins = ([r.origin for r in reqs]
                   if any(r.origin for r in reqs) else None)
        return self._pipe.submit(rows, acquire=acquire,
                                 prioritized=prio, origins=origins,
                                 trace_id=trace_id)

    # ------------------------------------------------------------------
    # settle / fan-out
    # ------------------------------------------------------------------

    def _on_demoted(self, names) -> None:
        """Tiering demote listener (engine lock held — O(names) only):
        drop demoted keys from the name→row cache so their next request
        misses, re-interns, and triggers promotion."""
        cache = self._rows
        for name in names:
            cache.pop(name, None)

    def _pipe_settled(self, seq: int, verdicts) -> None:
        """DispatchPipeline on_settle hook (any settling thread, pipeline
        lock held): release one depth slot back to the ingest loop."""
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(self._slots.release)

    async def _settle_loop(self) -> None:
        """Settles flushed batches strictly in dispatch order and fans
        each batch's verdicts out to its request futures."""
        obs = self._s.obs
        while True:
            ticket, reqs, batch_tr = await self._settle_q.get()
            verdicts = await asyncio.to_thread(ticket.result)
            if self._inflight_reqs and self._inflight_reqs[0] is reqs:
                self._inflight_reqs.popleft()
            self._inflight -= len(reqs)
            obs_on = obs.enabled
            t_end = obs.spans.now_ns() if obs_on else 0
            now_ms = self._s.clock.now_ms() if obs_on else 0
            worst = None              # worst deadline overrun this batch
            allow = np.asarray(verdicts.allow)
            reason = np.asarray(verdicts.reason)
            wait = np.asarray(verdicts.wait_ms)
            for i, r in enumerate(reqs):
                lat_ns = (t_end - r.t0_ns) if obs_on else 0
                if obs_on:
                    obs.hist_request.record(lat_ns)
                    if r.trace_id:
                        # fan-out: the batch settles THIS request (the
                        # flow arrow back), then the request's terminal
                        # span closes its chain
                        if batch_tr:
                            obs.spans.link(batch_tr, r.trace_id, "verdict")
                        obs.spans.record(r.trace_id, "frontend.settle",
                                         r.t0_ns, t_end, n=1)
                    if now_ms > r.deadline_ms and (
                            worst is None or worst[1] < now_ms
                            - r.deadline_ms):
                        worst = (r.trace_id, now_ms - r.deadline_ms)
                if not r.future.done():
                    r.future.set_result(RequestVerdict(
                        bool(allow[i]), int(reason[i]), int(wait[i]),
                        lat_ns / 1e6, r.trace_id))
            if obs_on:
                if worst is not None:
                    # SLO trigger: pin the worst-overrun request's chain
                    # (rate-limited per kind inside the recorder)
                    obs.flight.trigger("deadline_miss", root=worst[0],
                                       note=f"overrun_ms={worst[1]}",
                                       worst_ms=worst[1])
                obs.flight.note_requests(len(reqs))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def close(self) -> None:
        """Idempotent; callable from any thread (``Sentinel.close()``
        runs it via the shutdown registry). Pending futures fail with
        :class:`FrontendClosed`; device work already dispatched settles
        through the pipeline so engine bookkeeping stays consistent."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                on_loop = asyncio.get_running_loop() is loop
            except RuntimeError:
                on_loop = False
            if on_loop:
                self._shutdown_on_loop()
            else:
                loop.call_soon_threadsafe(self._shutdown_on_loop)
        # settle every dispatched batch (the settle task is dying with
        # the loop) — blocking, but terminal; bookkeeping must land
        self._pipe.flush()

    def _shutdown_on_loop(self) -> None:
        for task in (self._run_task, self._settle_task):
            if task is not None:
                task.cancel()
        exc = FrontendClosed("ingest front end closed before verdict")
        dropped = self.queue.take_all()
        for batch in list(self._inflight_reqs):
            dropped.extend(batch)
        self._inflight_reqs.clear()
        self._inflight = 0
        for req in dropped:
            if not req.future.done():
                req.future.set_exception(exc)
            elif not req.future.cancelled():
                req.future.exception()      # mark retrieved either way
