"""Serving front end: the async ingest tier above the dispatch pipeline.

* frontend/batcher.py — :class:`AdaptiveBatcher`, the deadline-driven
  adaptive batching loop (flush at ``min(B_max, oldest-deadline)``)
  with per-request future fan-out over the PR 6 DispatchPipeline;
* frontend/server.py — aiohttp HTTP endpoint + app factory so a
  service owner can POST an entry and get a verdict;
* frontend/workloads.py — the deterministic seeded workload zoo
  (steady, diurnal, flash crowd, Zipf hot keys, priority mix, slow
  consumer) that benchmarks/serving_bench.py replays through the real
  front end.

Operational guide: docs/OPERATIONS.md "Serving front end".
"""

from sentinel_tpu.frontend.batcher import (
    FLUSH_DEADLINE, FLUSH_FULL, FLUSH_IDLE, FRONTEND_BATCH_ENV,
    FRONTEND_BUDGET_ENV, FRONTEND_DEADLINE_ENV, FRONTEND_IDLE_ENV,
    FRONTEND_QUEUE_ENV, AdaptiveBatcher, FrontendClosed, IngestOverload,
    IngestQueue, RequestVerdict, frontend_batch_max, frontend_budget_ms,
    frontend_deadline_ms, frontend_idle_ms, frontend_queue_max,
)

__all__ = [
    "AdaptiveBatcher", "IngestQueue", "RequestVerdict",
    "IngestOverload", "FrontendClosed",
    "FLUSH_FULL", "FLUSH_DEADLINE", "FLUSH_IDLE",
    "FRONTEND_BATCH_ENV", "FRONTEND_DEADLINE_ENV", "FRONTEND_BUDGET_ENV",
    "FRONTEND_IDLE_ENV", "FRONTEND_QUEUE_ENV",
    "frontend_batch_max", "frontend_deadline_ms", "frontend_budget_ms",
    "frontend_idle_ms", "frontend_queue_max",
]
