"""The workload zoo: deterministic seeded traffic for the serving tier.

Every generator maps ``(seed, duration_ms, rate_rps, params)`` to the
SAME request timeline on every run — arrivals come from a seeded
``numpy.random.default_rng`` Poisson process (exponential
inter-arrivals, Lewis–Shedler thinning for the time-varying shapes) —
so benchmarks/serving_bench.py numbers are reproducible and the
ci_gate.py SLO band compares like against like, and the batcher parity
test can replay the exact stream twice.

Shapes (the traffic a flow-control deployment exists for):

* ``steady`` — constant-rate Poisson over a small uniform resource set;
  the SLO-gate baseline.
* ``diurnal`` — one sinusoidal ramp across the run (trough→peak→trough),
  the slow capacity sweep.
* ``flash_crowd`` — steady baseline with a ``spike_mult``× arrival
  burst over the middle ``spike_frac`` of the run, concentrated on one
  hot resource: the shed/queue stress the no-collapse gate probes.
* ``zipf_hot`` — Zipf(s≈1.1) resource popularity over a 1M-rank
  universe (CI-sized request counts touch only the hot head, so the
  intern cache sees realistic skew, not 1M interns).
* ``priority_mix`` — steady with a prioritized slice (exercises the
  PriorityWait occupy path through the front end).
* ``slow_consumer`` — square-wave bursts well above the sustainable
  rate with idle gaps: drives the queue to its backpressure bound so
  shed behavior is observable.
* ``overload_episode`` — the round-17 composite: steady tenant +
  flash-crowd spike + slow-consumer bursts overlapping in one
  timeline (independent per-component rngs merged by arrival time);
  the overload-controller gate's episode.

All are registered in :data:`WORKLOADS`; ``make(name, ...)`` is the
lookup used by the bench and tests.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

ZIPF_S = 1.1
ZIPF_UNIVERSE = 1_000_000

#: Largest universe the exact inverse-CDF path materializes (one f64
#: weight per rank). Above this, :func:`_zipf_ranks` switches to the
#: hybrid head-table + continuous-tail sampler so a 64M-key universe
#: (the round-15 tiering gate) costs O(head), not O(universe), memory.
ZIPF_EXACT_MAX = 1_000_000
_ZIPF_HEAD = 1 << 16


def _zipf_ranks(rng, n: int, s: float, universe: int) -> np.ndarray:
    """``n`` Zipf(s) ranks in ``[1, universe]``.

    ``universe <= ZIPF_EXACT_MAX``: exact inverse-CDF over the full
    materialized weight vector — bit-identical to the pre-round-15
    generator, so recorded bench baselines stay comparable.

    Larger universes: the first ``_ZIPF_HEAD`` ranks keep their exact
    discrete CDF (the head is where all the probability mass and all
    the hot-tier behavior live); the tail is drawn from the continuous
    power-law surrogate on ``[head+1, universe+1)`` via closed-form
    inverse CDF ``x = (u·(b^(1-s) − a^(1-s)) + a^(1-s))^(1/(1-s))``
    and floored to a rank. Nothing of size ``universe`` is ever
    allocated, and tail ranks stay long-tailed (a CI-sized run sees
    nearly every tail draw as a first-sight key — exactly the cold
    traffic the tiering gate needs)."""
    if universe <= ZIPF_EXACT_MAX:
        weights = 1.0 / np.power(
            np.arange(1, universe + 1, dtype=np.float64), s)
        cdf = np.cumsum(weights)
        cdf /= cdf[-1]
        return np.searchsorted(cdf, rng.random(n), side="right") + 1
    head = _ZIPF_HEAD
    head_cdf = np.cumsum(
        1.0 / np.power(np.arange(1, head + 1, dtype=np.float64), s))
    head_mass = head_cdf[-1]
    a, b = float(head + 1), float(universe + 1)
    one_m_s = 1.0 - s
    tail_mass = (b ** one_m_s - a ** one_m_s) / one_m_s
    u = rng.random(n) * (head_mass + tail_mass)
    ranks = np.empty(n, np.int64)
    in_head = u < head_mass
    ranks[in_head] = np.searchsorted(
        head_cdf, u[in_head], side="right") + 1
    ut = (u[~in_head] - head_mass) / tail_mass
    x = (ut * (b ** one_m_s - a ** one_m_s)
         + a ** one_m_s) ** (1.0 / one_m_s)
    ranks[~in_head] = np.minimum(
        np.floor(x).astype(np.int64), universe)
    return ranks


class Request(NamedTuple):
    """One scheduled request: fire at ``t_ms`` after stream start."""

    t_ms: float
    resource: str
    count: int
    prioritized: bool
    origin: str


def _arrivals(rng, duration_ms: float, rate_rps: float,
              intensity: Optional[Callable[[float], float]] = None,
              peak_mult: float = 1.0) -> List[float]:
    """Poisson arrival times in ``[0, duration_ms)``.

    Homogeneous at ``rate_rps`` when ``intensity`` is None; otherwise
    Lewis–Shedler thinning: candidates are drawn at the peak rate
    ``rate_rps * peak_mult`` and kept with probability
    ``intensity(t) / peak_mult`` (``intensity`` is the rate multiplier
    at time t, in ``[0, peak_mult]``)."""
    lam = (rate_rps * peak_mult) / 1000.0       # candidates per ms
    if lam <= 0:
        return []
    out: List[float] = []
    t = rng.exponential(1.0 / lam)
    while t < duration_ms:
        if intensity is None or rng.random() * peak_mult <= intensity(t):
            out.append(t)
        t += rng.exponential(1.0 / lam)
    return out


def _uniform_resources(rng, n_arrivals: int, n_resources: int,
                       prefix: str) -> List[str]:
    picks = rng.integers(0, n_resources, size=n_arrivals)
    return [f"{prefix}{int(i)}" for i in picks]


def steady(seed: int, duration_ms: float = 1000.0,
           rate_rps: float = 2000.0, n_resources: int = 16) -> List[Request]:
    """Constant-rate Poisson, uniform over ``n_resources`` resources."""
    rng = np.random.default_rng(seed)
    ts = _arrivals(rng, duration_ms, rate_rps)
    names = _uniform_resources(rng, len(ts), n_resources, "steady/")
    return [Request(t, r, 1, False, "") for t, r in zip(ts, names)]


def diurnal(seed: int, duration_ms: float = 1000.0,
            rate_rps: float = 2000.0, n_resources: int = 16,
            trough: float = 0.2) -> List[Request]:
    """One full day compressed into the run: sinusoidal rate between
    ``trough``× and 1× the nominal rate (trough at both ends)."""
    rng = np.random.default_rng(seed)
    span = 1.0 - trough

    def intensity(t: float) -> float:
        phase = (1.0 - math.cos(2.0 * math.pi * t / duration_ms)) / 2.0
        return trough + span * phase

    ts = _arrivals(rng, duration_ms, rate_rps, intensity, peak_mult=1.0)
    names = _uniform_resources(rng, len(ts), n_resources, "diurnal/")
    return [Request(t, r, 1, False, "") for t, r in zip(ts, names)]


def flash_crowd(seed: int, duration_ms: float = 1000.0,
                rate_rps: float = 2000.0, n_resources: int = 16,
                spike_mult: float = 8.0, spike_start: float = 0.4,
                spike_end: float = 0.6,
                hot_frac: float = 0.8) -> List[Request]:
    """Steady baseline with a ``spike_mult``× burst over the middle
    ``[spike_start, spike_end)`` fraction of the run; during the spike,
    ``hot_frac`` of requests hit ONE hot resource."""
    rng = np.random.default_rng(seed)
    lo, hi = spike_start * duration_ms, spike_end * duration_ms

    def intensity(t: float) -> float:
        return spike_mult if lo <= t < hi else 1.0

    ts = _arrivals(rng, duration_ms, rate_rps, intensity,
                   peak_mult=spike_mult)
    names = _uniform_resources(rng, len(ts), n_resources, "flash/")
    hot = rng.random(len(ts))
    out = []
    for i, t in enumerate(ts):
        r = names[i]
        if lo <= t < hi and hot[i] < hot_frac:
            r = "flash/hot"
        out.append(Request(t, r, 1, False, ""))
    return out


def zipf_hot(seed: int, duration_ms: float = 1000.0,
             rate_rps: float = 2000.0, s: float = ZIPF_S,
             universe: int = ZIPF_UNIVERSE) -> List[Request]:
    """Zipf(s) popularity over ``universe`` ranks: rank k drawn with
    probability ∝ 1/k^s via inverse-CDF, so the head is hot and the
    tail is long (a CI-sized run touches only a few hundred distinct
    resources out of the default 1M universe). ``universe`` scales to
    the tens of millions without materializing a key list — see
    :func:`_zipf_ranks`."""
    rng = np.random.default_rng(seed)
    ts = _arrivals(rng, duration_ms, rate_rps)
    ranks = _zipf_ranks(rng, len(ts), s, universe)
    return [Request(t, f"zipf/r{int(k)}", 1, False, "")
            for t, k in zip(ts, ranks)]


def priority_mix(seed: int, duration_ms: float = 1000.0,
                 rate_rps: float = 2000.0, n_resources: int = 8,
                 prio_frac: float = 0.2) -> List[Request]:
    """Steady traffic where ``prio_frac`` of requests are prioritized
    (PriorityWait occupy path) and carry a distinct origin."""
    rng = np.random.default_rng(seed)
    ts = _arrivals(rng, duration_ms, rate_rps)
    names = _uniform_resources(rng, len(ts), n_resources, "prio/")
    prio = rng.random(len(ts)) < prio_frac
    return [Request(t, r, 1, bool(p), "gold" if p else "bronze")
            for t, r, p in zip(ts, names, prio)]


def slow_consumer(seed: int, duration_ms: float = 1000.0,
                  rate_rps: float = 2000.0, n_resources: int = 4,
                  burst_mult: float = 16.0, period_ms: float = 200.0,
                  duty: float = 0.25) -> List[Request]:
    """Square-wave bursts at ``burst_mult``× nominal for ``duty`` of
    each ``period_ms``, silence otherwise — offered load far above the
    sustainable rate, so the ingest queue hits ``queue_max`` and sheds
    (the backpressure probe)."""
    rng = np.random.default_rng(seed)

    def intensity(t: float) -> float:
        return burst_mult if (t % period_ms) < duty * period_ms else 0.0

    ts = _arrivals(rng, duration_ms, rate_rps, intensity,
                   peak_mult=burst_mult)
    names = _uniform_resources(rng, len(ts), n_resources, "slow/")
    return [Request(t, r, 1, False, "") for t, r in zip(ts, names)]


def overload_episode(seed: int, duration_ms: float = 1000.0,
                     rate_rps: float = 2000.0, n_resources: int = 16,
                     steady_frac: float = 0.5, spike_mult: float = 8.0,
                     spike_start: float = 0.3, spike_end: float = 0.6,
                     hot_frac: float = 0.8, burst_mult: float = 16.0,
                     burst_period_ms: float = 200.0,
                     burst_duty: float = 0.25,
                     burst_frac: float = 0.25) -> List[Request]:
    """The round-17 controller-gate composite: a steady tenant that
    must keep its SLO, PLUS a flash-crowd spike on one hot resource,
    PLUS slow-consumer square-wave bursts — all three overlapping in
    one timeline. Component streams draw from independent seeded rngs
    (``seed``, ``seed+1``, ``seed+2``) and merge sorted by arrival
    time, so each component is individually deterministic and the
    composite replays exactly. The steady slice keeps the ``steady/``
    prefix — the gate scores ITS latency under the other two's abuse."""
    parts: List[Request] = []
    parts.extend(steady(seed, duration_ms,
                        rate_rps * steady_frac, n_resources))
    parts.extend(flash_crowd(
        seed + 1, duration_ms,
        rate_rps * max(0.0, 1.0 - steady_frac - burst_frac),
        n_resources, spike_mult, spike_start, spike_end, hot_frac))
    parts.extend(slow_consumer(
        seed + 2, duration_ms, rate_rps * burst_frac,
        max(1, n_resources // 4), burst_mult, burst_period_ms,
        burst_duty))
    parts.sort(key=lambda r: (r.t_ms, r.resource))
    return parts


#: name → generator; every generator is ``f(seed, duration_ms,
#: rate_rps, **shape_params) -> List[Request]`` and fully deterministic
#: for a given argument tuple.
WORKLOADS: Dict[str, Callable[..., List[Request]]] = {
    "steady": steady,
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "zipf_hot": zipf_hot,
    "priority_mix": priority_mix,
    "slow_consumer": slow_consumer,
    "overload_episode": overload_episode,
}


def make(name: str, seed: int, **kwargs) -> List[Request]:
    """Generate workload ``name`` (see :data:`WORKLOADS`)."""
    try:
        fn = WORKLOADS[name]
    except KeyError:
        raise KeyError(
            f"unknown workload {name!r}; have {sorted(WORKLOADS)}") from None
    return fn(seed, **kwargs)
