"""Writable-datasource registry (reference
``WritableDataSourceRegistry.java``): the ``setRules`` command persists a
successful in-memory load through the registered writable source for that
rule type (``ModifyRulesCommandHandler.java:47-77``).

The reference's registry is JVM-global static state; here the registry is an
ordinary object so multiple :class:`~sentinel_tpu.runtime.Sentinel` instances
in one process don't cross-write each other's rule files — a module-level
``default_registry`` keeps the one-instance case as convenient as the
reference.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from sentinel_tpu.datasource.base import WritableDataSource


class WritableDataSourceRegistry:
    def __init__(self) -> None:
        self._sources: Dict[str, WritableDataSource] = {}
        self._lock = threading.Lock()

    def register(self, rule_type: str, source: WritableDataSource) -> None:
        with self._lock:
            self._sources[rule_type] = source

    def get(self, rule_type: str) -> Optional[WritableDataSource]:
        with self._lock:
            return self._sources.get(rule_type)

    def write_if_registered(self, rule_type: str, rules: List[Any]) -> bool:
        src = self.get(rule_type)
        if src is None:
            return False
        src.write(rules)
        return True

    def clear(self) -> None:
        with self._lock:
            self._sources.clear()


default_registry = WritableDataSourceRegistry()
