"""JSON converters/encoders per rule type — the ``Converter<S,T>`` instances
every datasource is constructed with (reference demos wire
``new Converter<String, List<FlowRule>>`` around fastjson; here the codecs
are shared with the transport command handlers so file contents, dashboard
payloads, and datasource payloads are one format)."""

from __future__ import annotations

from typing import Any, Callable, List

from sentinel_tpu.rules import codec


def rule_converter(rule_type: str) -> Callable[[str], List[Any]]:
    if rule_type not in codec.RULE_TYPES:
        raise ValueError(f"unknown rule type: {rule_type}")
    return lambda text: codec.rules_from_json(rule_type, text or "[]")


def rule_encoder(rule_type: str) -> Callable[[List[Any]], str]:
    if rule_type not in codec.RULE_TYPES:
        raise ValueError(f"unknown rule type: {rule_type}")
    return lambda rules: codec.rules_to_json(rule_type, rules)
