"""Datasource SPI (reference ``sentinel-datasource-extension/.../datasource``).

* :class:`ReadableDataSource` — ``loadConfig()`` + ``getProperty()``; register
  the property into a rule manager cell and rule updates flow automatically
  (``AbstractDataSource.java:1-40``).
* :class:`AutoRefreshDataSource` — poll loop (default 3 s,
  ``AutoRefreshDataSource.java:32-45``).
* :class:`FileRefreshableDataSource` — mtime-gated file reload.
* :class:`FileWritableDataSource` — persistence for dashboard pushes.

The refresh loop takes the clock so tests can drive it virtually via
``refresh_now()``.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Generic, Optional, TypeVar

from sentinel_tpu.core.logs import record_log
from sentinel_tpu.core.property import SentinelProperty

S = TypeVar("S")
T = TypeVar("T")

Converter = Callable[[S], T]

DEFAULT_REFRESH_MS = 3000


class ReadableDataSource(Generic[S, T]):
    def load_config(self) -> T:
        raise NotImplementedError

    def get_property(self) -> SentinelProperty:
        raise NotImplementedError

    def close(self) -> None:
        pass


class WritableDataSource(Generic[T]):
    def write(self, value: T) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class AbstractDataSource(ReadableDataSource[S, T]):
    """converter + property cell; subclasses implement ``read_source``."""

    def __init__(self, converter: Converter):
        if converter is None:
            raise ValueError("converter can't be null")
        self.converter = converter
        self.property: SentinelProperty = SentinelProperty()

    def read_source(self) -> S:
        raise NotImplementedError

    def load_config(self) -> T:
        return self.converter(self.read_source())

    def get_property(self) -> SentinelProperty:
        return self.property


class AutoRefreshDataSource(AbstractDataSource[S, T]):
    """Background poll loop; ``is_modified()`` short-circuits no-op reloads."""

    def __init__(self, converter: Converter,
                 refresh_ms: int = DEFAULT_REFRESH_MS, *,
                 start_thread: bool = True):
        super().__init__(converter)
        if refresh_ms <= 0:
            raise ValueError("refresh_ms must be positive")
        self.refresh_ms = refresh_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start_thread = start_thread

    def initialize(self) -> None:
        """First load + start the refresh loop (ctor tail in the reference)."""
        try:
            self.property.update_value(self.load_config())
        except Exception as exc:
            record_log().warning("datasource initial load failed: %r", exc)
        if self._start_thread:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="sentinel-ds-refresh")
            self._thread.start()

    def is_modified(self) -> bool:
        return True

    def refresh_now(self) -> bool:
        """One poll step (test hook + loop body). True if value updated."""
        try:
            if not self.is_modified():
                return False
            return self.property.update_value(self.load_config())
        except Exception as exc:
            record_log().warning("datasource refresh failed: %r", exc)
            return False

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_ms / 1000.0):
            self.refresh_now()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


class FileRefreshableDataSource(AutoRefreshDataSource[str, T]):
    """Re-reads a file when its mtime changes
    (``FileRefreshableDataSource.java``)."""

    def __init__(self, path: str, converter: Converter,
                 refresh_ms: int = DEFAULT_REFRESH_MS,
                 encoding: str = "utf-8", *, start_thread: bool = True):
        super().__init__(converter, refresh_ms, start_thread=start_thread)
        self.path = os.path.abspath(path)
        self.encoding = encoding
        self._last_mtime: float = -1.0
        self.initialize()

    def read_source(self) -> str:
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            self._last_mtime = -1.0
            return ""
        self._last_mtime = st.st_mtime
        with open(self.path, encoding=self.encoding) as fh:
            return fh.read()

    def is_modified(self) -> bool:
        try:
            return os.stat(self.path).st_mtime != self._last_mtime
        except FileNotFoundError:
            return self._last_mtime != -1.0


class FileWritableDataSource(WritableDataSource[T]):
    """Serializes values to a file (``FileWritableDataSource.java``)."""

    def __init__(self, path: str, encoder: Callable[[T], str],
                 encoding: str = "utf-8"):
        self.path = os.path.abspath(path)
        self.encoder = encoder
        self.encoding = encoding
        self._lock = threading.Lock()

    def write(self, value: T) -> None:
        text = self.encoder(value)
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding=self.encoding) as fh:
                fh.write(text)
            os.replace(tmp, self.path)
