"""HTTP datasources (reference pull sources — Consul/Eureka/Spring-Cloud-
Config style: poll a config endpoint, short-circuit on unchanged content;
optional long-poll with an index/ETag the way Consul blocks queries).

``HttpRefreshableDataSource`` GETs ``url`` every ``refresh_ms`` and updates
the property only when the body changed (ETag/Last-Modified respected when
the server provides them). ``HttpLongPollDataSource`` adds Consul-style
blocking reads: pass ``index_header`` (e.g. ``X-Consul-Index``) and the
source re-issues the request with the last seen index as a query param so
the server can hold the request until a change.
"""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Dict, Optional

from sentinel_tpu.core.logs import record_log
from sentinel_tpu.datasource.base import (
    AutoRefreshDataSource, Converter, DEFAULT_REFRESH_MS, T,
)


class HttpRefreshableDataSource(AutoRefreshDataSource[str, T]):
    def __init__(self, url: str, converter: Converter,
                 refresh_ms: int = DEFAULT_REFRESH_MS, *,
                 headers: Optional[Dict[str, str]] = None,
                 timeout_s: float = 5.0, start_thread: bool = True):
        self.url = url
        self.headers = dict(headers or {})
        self.timeout_s = timeout_s
        self._etag: Optional[str] = None
        self._last_modified: Optional[str] = None
        self._last_body: Optional[str] = None
        super().__init__(converter, refresh_ms, start_thread=start_thread)
        self.initialize()

    def _request(self) -> urllib.request.Request:
        req = urllib.request.Request(self.url, headers=self.headers)
        if self._etag:
            req.add_header("If-None-Match", self._etag)
        if self._last_modified:
            req.add_header("If-Modified-Since", self._last_modified)
        return req

    def read_source(self) -> str:
        try:
            with urllib.request.urlopen(self._request(),
                                        timeout=self.timeout_s) as r:
                body = r.read().decode("utf-8")
                # commit validators only after the body arrived intact — a
                # failed read must not pin future polls to 304/stale-body
                self._etag = r.headers.get("ETag") or self._etag
                self._last_modified = (r.headers.get("Last-Modified")
                                       or self._last_modified)
                self._last_body = body
                return body
        except urllib.error.HTTPError as exc:
            if exc.code == 304 and self._last_body is not None:
                return self._last_body       # not modified
            raise

    def is_modified(self) -> bool:
        # conditional requests make the full read cheap; decide there
        return True

    def refresh_now(self) -> bool:
        try:
            before = self._last_body
            body = self.read_source()
            # a blocking read (long-poll) can outlive close(): a response
            # arriving after stop must not fire listeners
            if self._stop.is_set():
                return False
            if body == before:
                return False
            return self.property.update_value(self.converter(body))
        except Exception as exc:
            record_log().warning("http datasource refresh failed: %r", exc)
            return False


class HttpLongPollDataSource(HttpRefreshableDataSource[T]):
    """Blocking-query pull (Consul watch style): the server holds the
    request until the watched key changes past ``index``."""

    def __init__(self, url: str, converter: Converter, *,
                 index_header: str = "X-Consul-Index",
                 index_param: str = "index",
                 wait: str = "25s",
                 refresh_ms: int = 1_000,     # near-immediate re-poll
                 **kw):
        self.index_header = index_header
        self.index_param = index_param
        self.wait = wait
        self._index: Optional[str] = None
        super().__init__(url, converter, refresh_ms, **kw)

    def _request(self) -> urllib.request.Request:
        url = self.url
        if self._index:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}{self.index_param}={self._index}&wait={self.wait}"
        return urllib.request.Request(url, headers=self.headers)

    def read_source(self) -> str:
        with urllib.request.urlopen(self._request(),
                                    timeout=self.timeout_s + 30) as r:
            body = r.read().decode("utf-8")
            # commit the blocking-query index only after the body arrived —
            # otherwise a dropped connection skips this change forever
            self._index = r.headers.get(self.index_header) or self._index
            self._last_body = body
            return body

class InProcessDataSource(AutoRefreshDataSource[object, T]):
    """Push source for embedding apps (reference push datasources collapse
    to this when the transport is in-process): call :meth:`push` with the
    raw source value and every registered listener converges — same
    property-cell choke point as Nacos/ZK/etcd listeners (SURVEY §3.5b)."""

    def __init__(self, converter: Converter, initial=None):
        self._value = initial
        super().__init__(converter, refresh_ms=3_600_000, start_thread=False)
        if initial is not None:      # no spurious converter(None) at init
            self.initialize()

    def read_source(self):
        return self._value

    def push(self, value) -> bool:
        self._value = value
        return self.property.update_value(self.converter(value))
