"""Dynamic rule datasources (reference ``sentinel-datasource-extension``):
readable sources feed rule properties; writable sources persist dashboard
pushes (SURVEY §2.2, L5)."""

from sentinel_tpu.datasource.base import (  # noqa: F401
    AbstractDataSource, AutoRefreshDataSource, FileRefreshableDataSource,
    FileWritableDataSource, ReadableDataSource, WritableDataSource,
)
from sentinel_tpu.datasource.registry import (  # noqa: F401
    WritableDataSourceRegistry, default_registry,
)
from sentinel_tpu.datasource.converters import rule_converter, rule_encoder  # noqa: F401
from sentinel_tpu.datasource.http import (  # noqa: F401
    HttpLongPollDataSource, HttpRefreshableDataSource, InProcessDataSource,
)
from sentinel_tpu.datasource.named import (  # noqa: F401
    ApolloDataSource, ConsulDataSource, EtcdDataSource, EurekaDataSource,
    NacosDataSource, RedisDataSource, SpringCloudConfigDataSource,
    ZooKeeperDataSource,
)
