"""Named datasource drivers (reference ``sentinel-datasource-*`` modules).

PUSH drivers (rule change visible without waiting out a poll interval —
the reference's listener/watch semantics):

- :class:`ConsulDataSource` — KV blocking queries (``X-Consul-Index``),
  like ``sentinel-datasource-consul``'s long-poll watch.
- :class:`NacosDataSource` — the Nacos config LISTENER long-poll protocol
  (``/v1/cs/configs/listener`` with MD5 bookkeeping, 30 s hold), like
  ``sentinel-datasource-nacos``'s ``ConfigService.addListener``; degrades
  to conditional-GET polling when the listener endpoint is unavailable.
- :class:`EtcdDataSource` — v3 gRPC-gateway ``/v3/watch`` streaming watch
  with ``/v3/kv/range`` for the initial read and as the poll fallback,
  like ``sentinel-datasource-etcd``'s ``Watch.watch``.
- :class:`ZooKeeperDataSource` — node data watch (kazoo ``DataWatch``,
  client injectable for tests), like ``sentinel-datasource-zookeeper``'s
  Curator ``NodeCache`` listener.
- :class:`RedisDataSource` — initial GET + pub/sub channel updates,
  like ``sentinel-datasource-redis``; requires the ``redis`` package
  (gated import — this build image doesn't ship it).

Pull drivers (each system only offers a fetch API):

- :class:`EurekaDataSource` / :class:`SpringCloudConfigDataSource` /
  :class:`ApolloDataSource` — plain conditional-GET polls over each
  system's config URL shape.
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from sentinel_tpu.core.logs import record_log
from sentinel_tpu.datasource.base import (
    AutoRefreshDataSource, Converter, T,
)
from sentinel_tpu.datasource.http import (
    HttpLongPollDataSource, HttpRefreshableDataSource,
)

# Nacos listener framing (reference NacosDataSource / Nacos open API)
_NACOS_WORD_SEP = "\x02"
_NACOS_LINE_SEP = "\x01"


class ConsulDataSource(HttpLongPollDataSource[T]):
    def __init__(self, host: str, port: int, rule_key: str,
                 converter: Converter, *, token: Optional[str] = None,
                 wait: str = "25s", **kw):
        headers = dict(kw.pop("headers", {}) or {})
        if token:
            headers["X-Consul-Token"] = token
        super().__init__(
            f"http://{host}:{port}/v1/kv/{rule_key}?raw",
            converter, index_header="X-Consul-Index", wait=wait,
            headers=headers, **kw)


class NacosDataSource(HttpRefreshableDataSource[T]):
    """Nacos config listener (PUSH): each refresh cycle issues the open-API
    long-poll — POST ``/v1/cs/configs/listener`` with
    ``dataId^2group^2md5[^2tenant]^1`` and a ``Long-Pulling-Timeout``
    header — which the server holds until the config's MD5 changes (or the
    hold expires). A change answers immediately → the config is fetched at
    once, so updates land in ~RTT instead of a poll interval. If the
    listener endpoint is unavailable the driver degrades to plain
    conditional-GET polling every ``refresh_ms``."""

    def __init__(self, server_addr: str, data_id: str, group: str,
                 converter: Converter, *, namespace: str = "",
                 refresh_ms: int = 3000, listen_timeout_ms: int = 30_000,
                 **kw):
        self.data_id = data_id
        self.group = group
        self.namespace = namespace
        self.listen_timeout_ms = listen_timeout_ms
        self._listener_url = f"http://{server_addr}/nacos/v1/cs/configs/listener"
        self._md5 = ""
        # monotonic deadline before which the listener is not attempted —
        # a failed long-poll falls back to polling for one cooldown, then
        # re-probes (the reference listener keeps retrying; a permanent
        # downgrade would silently lose push semantics forever)
        self._listener_retry_at = 0.0
        self.listener_cooldown_s = 30.0
        qs = f"dataId={urllib.parse.quote(data_id)}" \
             f"&group={urllib.parse.quote(group)}"
        if namespace:
            qs += f"&tenant={urllib.parse.quote(namespace)}"
        super().__init__(f"http://{server_addr}/nacos/v1/cs/configs?{qs}",
                         converter, refresh_ms, **kw)

    def read_source(self) -> str:
        body = super().read_source()
        self._md5 = hashlib.md5(body.encode("utf-8")).hexdigest() if body \
            else ""
        return body

    def _listen_once(self) -> bool:
        """One listener long-poll → True when the server reports a change
        (caller re-reads the config)."""
        fields = [self.data_id, self.group, self._md5]
        if self.namespace:
            fields.append(self.namespace)
        listening = _NACOS_WORD_SEP.join(fields) + _NACOS_LINE_SEP
        data = urllib.parse.urlencode(
            {"Listening-Configs": listening}).encode()
        req = urllib.request.Request(
            self._listener_url, data=data,
            headers={**self.headers,
                     "Long-Pulling-Timeout": str(self.listen_timeout_ms),
                     "Content-Type": "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(
                req, timeout=self.listen_timeout_ms / 1000.0 + 10) as r:
            return bool(r.read().decode("utf-8").strip())

    def _listener_active(self) -> bool:
        import time as _time

        return _time.monotonic() >= self._listener_retry_at

    def refresh_now(self) -> bool:
        if not self._listener_active():
            return super().refresh_now()     # poll fallback (cooldown)
        try:
            changed = self._listen_once()
        except Exception as exc:
            # broad on purpose (base-class refresh contract): ANY listener
            # failure — IncompleteRead, protocol error, refused — must not
            # kill the refresh thread; poll for a cooldown, then re-probe
            import time as _time

            record_log().warning(
                "nacos listener unavailable (%r); polling for %.0fs",
                exc, self.listener_cooldown_s)
            self._listener_retry_at = (_time.monotonic()
                                       + self.listener_cooldown_s)
            return super().refresh_now()
        if self._stop.is_set() or not changed:
            return False
        return super().refresh_now()

    def _loop(self) -> None:
        # push mode paces itself by the server-held long-poll; the poll
        # fallback keeps the configured interval
        while not self._stop.wait(
                0.05 if self._listener_active()
                else self.refresh_ms / 1000.0):
            self.refresh_now()


class EtcdDataSource(HttpRefreshableDataSource[T]):
    """etcd v3 over the gRPC-gateway (PUSH): initial read + poll fallback
    via POST ``/v3/kv/range`` (base64 key, value base64-decoded before
    conversion), plus a WATCH stream — POST ``/v3/watch`` with a
    ``create_request``, the gateway streaming one JSON object per change —
    so updates land in ~RTT like the reference driver's ``Watch.watch``.
    The watch thread reconnects after errors; the poll loop remains as the
    safety net (its interval only matters while the watch is down)."""

    def __init__(self, host: str, port: int, key: str,
                 converter: Converter, *, refresh_ms: int = 3000,
                 watch: bool = True, watch_reconnect_s: float = 2.0,
                 watch_idle_timeout_s: float = 120.0, **kw):
        self._range_key = base64.b64encode(key.encode()).decode()
        self._watch_url = f"http://{host}:{port}/v3/watch"
        self._watch_reconnect_s = watch_reconnect_s
        self._watch_idle_timeout_s = watch_idle_timeout_s
        super().__init__(f"http://{host}:{port}/v3/kv/range",
                         converter, refresh_ms, **kw)
        self._watch_thread: Optional[threading.Thread] = None
        if watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="sentinel-etcd-watch")
            self._watch_thread.start()

    def _request(self) -> urllib.request.Request:
        body = json.dumps({"key": self._range_key}).encode()
        return urllib.request.Request(
            self.url, data=body,
            headers={**self.headers, "Content-Type": "application/json"})

    def read_source(self) -> str:
        with urllib.request.urlopen(self._request(),
                                    timeout=self.timeout_s) as r:
            payload = json.loads(r.read().decode("utf-8"))
        kvs = payload.get("kvs") or []
        body = (base64.b64decode(kvs[0]["value"]).decode("utf-8")
                if kvs else "")
        self._last_body = body
        return body

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                body = json.dumps(
                    {"create_request": {"key": self._range_key}}).encode()
                req = urllib.request.Request(
                    self._watch_url, data=body,
                    headers={**self.headers,
                             "Content-Type": "application/json"})
                # idle read timeout: an LB/NAT can drop the long-lived
                # stream without FIN, which would otherwise block this
                # thread forever with the reconnect path unreachable;
                # timing out a healthy-but-quiet stream just re-creates
                # the watch, which is harmless
                with urllib.request.urlopen(
                        req, timeout=self._watch_idle_timeout_s) as r:
                    for line in r:               # one JSON object per change
                        if self._stop.is_set():
                            return
                        self._on_watch_line(line)
            except Exception as exc:
                # broad on purpose: a malformed document (converter
                # KeyError), IncompleteRead, or protocol error must
                # reconnect the watch, not kill the thread forever
                if self._stop.is_set():
                    return
                record_log().warning("etcd watch dropped (%r); retrying",
                                     exc)
            self._stop.wait(self._watch_reconnect_s)

    def _on_watch_line(self, line: bytes) -> None:
        line = line.strip()
        if not line:
            return
        doc = json.loads(line.decode("utf-8"))
        events = (doc.get("result") or {}).get("events") or []
        for evt in events:
            kv = evt.get("kv") or {}
            raw = kv.get("value")
            body = (base64.b64decode(raw).decode("utf-8")
                    if raw else "")
            if body != self._last_body:
                self._last_body = body
                self.property.update_value(self.converter(body))

    def close(self) -> None:
        super().close()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=1.0)
            self._watch_thread = None


class EurekaDataSource(HttpRefreshableDataSource[T]):
    """Config served by an app registered in Eureka — the reference driver
    resolves an instance and GETs its rule endpoint; here the resolved URL
    is given directly (service discovery stays the caller's concern)."""

    def __init__(self, rule_url: str, converter: Converter, **kw):
        super().__init__(rule_url, converter, **kw)


class SpringCloudConfigDataSource(HttpRefreshableDataSource[T]):
    def __init__(self, server_addr: str, application: str, profile: str,
                 label: str, key: str, converter: Converter, **kw):
        self._key = key
        super().__init__(
            f"http://{server_addr}/{application}/{profile}/{label}",
            converter, **kw)

    def read_source(self) -> str:
        # _last_body stays the RAW envelope (the base class's 304 path
        # replays it through this extraction again)
        raw = super().read_source()
        try:
            doc = json.loads(raw)
            for ps in doc.get("propertySources", []):
                src = ps.get("source", {})
                if self._key in src:
                    return str(src[self._key])
        except (ValueError, AttributeError):
            pass
        return ""


class ApolloDataSource(HttpRefreshableDataSource[T]):
    def __init__(self, server_addr: str, app_id: str, cluster: str,
                 namespace: str, key: str, converter: Converter, **kw):
        self._key = key
        super().__init__(
            f"http://{server_addr}/configs/{app_id}/{cluster}/{namespace}",
            converter, **kw)

    def read_source(self) -> str:
        raw = super().read_source()    # _last_body stays the raw envelope
        try:
            return str(json.loads(raw).get("configurations", {})
                       .get(self._key, ""))
        except (ValueError, AttributeError):
            return ""


class ZooKeeperDataSource:
    """ZooKeeper node watch (reference ``sentinel-datasource-zookeeper``:
    Curator ``NodeCache`` + listener → here a kazoo ``DataWatch``).

    ``client`` injects any kazoo-compatible object (``start()``,
    ``DataWatch(path, fn)`` where ``fn(data, stat)`` fires on every change,
    ``stop()``/``close()``) — tests drive a fake; production passes a real
    ``kazoo.client.KazooClient`` or lets the gated import construct one."""

    def __init__(self, hosts: str, path: str, converter: Converter, *,
                 client=None):
        from sentinel_tpu.core.property import SentinelProperty

        if client is None:
            try:
                from kazoo.client import KazooClient
            except ImportError as exc:
                raise ImportError(
                    "ZooKeeperDataSource requires the 'kazoo' package (or "
                    "pass a kazoo-compatible client=); install it or use a "
                    "file/HTTP datasource") from exc
            client = KazooClient(hosts=hosts)
        self.converter = converter
        self.property = SentinelProperty()
        self._client = client
        self._client.start()
        # DataWatch fires immediately with the current value, then on every
        # change — the NodeCache initial-load + listener semantics
        self._client.DataWatch(path, self._on_change)

    def _on_change(self, data, stat, *_) -> None:
        body = data.decode("utf-8") if isinstance(data, bytes) else (data or "")
        try:
            self.property.update_value(self.converter(body))
        except Exception as exc:
            record_log().warning("zookeeper datasource convert failed: %r",
                                 exc)

    def get_property(self):
        return self.property

    def close(self) -> None:
        try:
            self._client.stop()
        finally:
            close = getattr(self._client, "close", None)
            if close is not None:
                close()


class RedisDataSource:
    """Initial GET + pub/sub update channel (``sentinel-datasource-redis``).
    Requires the ``redis`` package; constructing without it raises with a
    clear message (the build image doesn't bundle redis)."""

    def __init__(self, host: str, port: int, rule_key: str, channel: str,
                 converter: Converter, *, db: int = 0,
                 password: Optional[str] = None):
        try:
            import redis
        except ImportError as exc:
            raise ImportError(
                "RedisDataSource requires the 'redis' package; install it "
                "or use a file/HTTP datasource") from exc
        from sentinel_tpu.core.property import SentinelProperty

        self.converter = converter
        self.property = SentinelProperty()
        self._client = redis.Redis(host=host, port=port, db=db,
                                   password=password)
        initial = self._client.get(rule_key)
        if initial is not None:
            self.property.update_value(converter(initial.decode("utf-8")))
        self._pubsub = self._client.pubsub()
        self._pubsub.subscribe(**{channel: self._on_message})
        self._thread = self._pubsub.run_in_thread(sleep_time=0.1,
                                                  daemon=True)

    def _on_message(self, message) -> None:
        if message.get("type") == "message":
            data = message["data"]
            if isinstance(data, bytes):
                data = data.decode("utf-8")
            self.property.update_value(self.converter(data))

    def get_property(self):
        return self.property

    def close(self) -> None:
        self._thread.stop()
        self._pubsub.close()
        self._client.close()
