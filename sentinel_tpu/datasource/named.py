"""Named datasource drivers (reference ``sentinel-datasource-*`` modules).

Thin, conventions-encoded wrappers over the generic HTTP sources — each
reference driver reduces to "fetch this URL shape, watch it this way":

- :class:`ConsulDataSource` — KV blocking queries (``X-Consul-Index``),
  like ``sentinel-datasource-consul``'s long-poll watch.
- :class:`NacosDataSource` — open-API config poll
  (``/nacos/v1/cs/configs``), like ``sentinel-datasource-nacos``'s
  listener (poll interval stands in for the push channel).
- :class:`EtcdDataSource` — v3 gRPC-gateway ``/v3/kv/range`` POST poll,
  like ``sentinel-datasource-etcd``.
- :class:`EurekaDataSource` / :class:`SpringCloudConfigDataSource` /
  :class:`ApolloDataSource` — plain conditional-GET polls over each
  system's config URL shape.
- :class:`RedisDataSource` — initial GET + pub/sub channel updates,
  like ``sentinel-datasource-redis``; requires the ``redis`` package
  (gated import — this build image doesn't ship it).
"""

from __future__ import annotations

import base64
import json
import urllib.parse
import urllib.request
from typing import Optional

from sentinel_tpu.datasource.base import Converter, T
from sentinel_tpu.datasource.http import (
    HttpLongPollDataSource, HttpRefreshableDataSource,
)


class ConsulDataSource(HttpLongPollDataSource[T]):
    def __init__(self, host: str, port: int, rule_key: str,
                 converter: Converter, *, token: Optional[str] = None,
                 wait: str = "25s", **kw):
        headers = dict(kw.pop("headers", {}) or {})
        if token:
            headers["X-Consul-Token"] = token
        super().__init__(
            f"http://{host}:{port}/v1/kv/{rule_key}?raw",
            converter, index_header="X-Consul-Index", wait=wait,
            headers=headers, **kw)


class NacosDataSource(HttpRefreshableDataSource[T]):
    def __init__(self, server_addr: str, data_id: str, group: str,
                 converter: Converter, *, namespace: str = "",
                 refresh_ms: int = 3000, **kw):
        qs = f"dataId={urllib.parse.quote(data_id)}" \
             f"&group={urllib.parse.quote(group)}"
        if namespace:
            qs += f"&tenant={urllib.parse.quote(namespace)}"
        super().__init__(f"http://{server_addr}/nacos/v1/cs/configs?{qs}",
                         converter, refresh_ms, **kw)


class EtcdDataSource(HttpRefreshableDataSource[T]):
    """etcd v3 over the gRPC-gateway: POST ``/v3/kv/range`` with the
    base64-encoded key; the value is base64-decoded before conversion."""

    def __init__(self, host: str, port: int, key: str,
                 converter: Converter, *, refresh_ms: int = 3000, **kw):
        self._range_key = base64.b64encode(key.encode()).decode()
        super().__init__(f"http://{host}:{port}/v3/kv/range",
                         converter, refresh_ms, **kw)

    def _request(self) -> urllib.request.Request:
        body = json.dumps({"key": self._range_key}).encode()
        return urllib.request.Request(
            self.url, data=body,
            headers={**self.headers, "Content-Type": "application/json"})

    def read_source(self) -> str:
        with urllib.request.urlopen(self._request(),
                                    timeout=self.timeout_s) as r:
            payload = json.loads(r.read().decode("utf-8"))
        kvs = payload.get("kvs") or []
        body = (base64.b64decode(kvs[0]["value"]).decode("utf-8")
                if kvs else "")
        self._last_body = body
        return body


class EurekaDataSource(HttpRefreshableDataSource[T]):
    """Config served by an app registered in Eureka — the reference driver
    resolves an instance and GETs its rule endpoint; here the resolved URL
    is given directly (service discovery stays the caller's concern)."""

    def __init__(self, rule_url: str, converter: Converter, **kw):
        super().__init__(rule_url, converter, **kw)


class SpringCloudConfigDataSource(HttpRefreshableDataSource[T]):
    def __init__(self, server_addr: str, application: str, profile: str,
                 label: str, key: str, converter: Converter, **kw):
        self._key = key
        super().__init__(
            f"http://{server_addr}/{application}/{profile}/{label}",
            converter, **kw)

    def read_source(self) -> str:
        # _last_body stays the RAW envelope (the base class's 304 path
        # replays it through this extraction again)
        raw = super().read_source()
        try:
            doc = json.loads(raw)
            for ps in doc.get("propertySources", []):
                src = ps.get("source", {})
                if self._key in src:
                    return str(src[self._key])
        except (ValueError, AttributeError):
            pass
        return ""


class ApolloDataSource(HttpRefreshableDataSource[T]):
    def __init__(self, server_addr: str, app_id: str, cluster: str,
                 namespace: str, key: str, converter: Converter, **kw):
        self._key = key
        super().__init__(
            f"http://{server_addr}/configs/{app_id}/{cluster}/{namespace}",
            converter, **kw)

    def read_source(self) -> str:
        raw = super().read_source()    # _last_body stays the raw envelope
        try:
            return str(json.loads(raw).get("configurations", {})
                       .get(self._key, ""))
        except (ValueError, AttributeError):
            return ""


class RedisDataSource:
    """Initial GET + pub/sub update channel (``sentinel-datasource-redis``).
    Requires the ``redis`` package; constructing without it raises with a
    clear message (the build image doesn't bundle redis)."""

    def __init__(self, host: str, port: int, rule_key: str, channel: str,
                 converter: Converter, *, db: int = 0,
                 password: Optional[str] = None):
        try:
            import redis
        except ImportError as exc:
            raise ImportError(
                "RedisDataSource requires the 'redis' package; install it "
                "or use a file/HTTP datasource") from exc
        from sentinel_tpu.core.property import SentinelProperty

        self.converter = converter
        self.property = SentinelProperty()
        self._client = redis.Redis(host=host, port=port, db=db,
                                   password=password)
        initial = self._client.get(rule_key)
        if initial is not None:
            self.property.update_value(converter(initial.decode("utf-8")))
        self._pubsub = self._client.pubsub()
        self._pubsub.subscribe(**{channel: self._on_message})
        self._thread = self._pubsub.run_in_thread(sleep_time=0.1,
                                                  daemon=True)

    def _on_message(self, message) -> None:
        if message.get("type") == "message":
            data = message["data"]
            if isinstance(data, bytes):
                data = data.decode("utf-8")
            self.property.update_value(self.converter(data))

    def get_property(self):
        return self.property

    def close(self) -> None:
        self._thread.stop()
        self._pubsub.close()
        self._client.close()
