"""sentinel-tpu: TPU-native flow control, circuit breaking and adaptive
protection — the capabilities of Alibaba Sentinel, rebuilt on JAX/XLA.

Quick start (reference README parity)::

    import sentinel_tpu as stpu

    sph = stpu.Sentinel()
    sph.load_flow_rules([stpu.FlowRule(resource="HelloWorld", count=20)])

    try:
        with sph.entry("HelloWorld"):
            do_something()
    except stpu.BlockException:
        do_fallback()
"""

from sentinel_tpu.core.clock import Clock, ManualClock, SystemClock, set_global_clock
from sentinel_tpu.core.config import SentinelConfig, load_config
from sentinel_tpu.core.context import (
    ContextScope,
    enter_context,
    exit_context,
    restore_context,
    snapshot_context,
)
from sentinel_tpu.core.errors import (
    AuthorityException,
    BlockException,
    BlockReason,
    CustomSlotException,
    DegradeException,
    ErrorEntryFreeError,
    FlowException,
    ParamFlowException,
    SystemBlockException,
)
from sentinel_tpu.core.initexec import InitExecutor, init_func
from sentinel_tpu.core.spi import (
    SERVICE_COMMAND_HANDLER,
    SERVICE_INIT_FUNC,
    SERVICE_PROCESSOR_SLOT,
    SpiLoader,
    spi,
)
from sentinel_tpu.engine.slots import DeviceSlot, DeviceSlotView, HostGate
from sentinel_tpu.rules.authority import STRATEGY_BLACK, STRATEGY_WHITE, AuthorityRule
from sentinel_tpu.rules.degrade import (
    GRADE_EXCEPTION_COUNT,
    GRADE_EXCEPTION_RATIO,
    GRADE_RT,
    DegradeRule,
)
from sentinel_tpu.rules.flow import (
    BEHAVIOR_DEFAULT,
    BEHAVIOR_RATE_LIMITER,
    BEHAVIOR_WARM_UP,
    BEHAVIOR_WARM_UP_RATE_LIMITER,
    GRADE_QPS,
    GRADE_THREAD,
    STRATEGY_CHAIN,
    STRATEGY_DIRECT,
    STRATEGY_RELATE,
    FlowRule,
)
from sentinel_tpu.rules.param_flow import (
    BEHAVIOR_RATE_LIMITER as PARAM_BEHAVIOR_RATE_LIMITER,
    ParamFlowItem,
    ParamFlowRule,
)
from sentinel_tpu.rules.system import SystemRule
from sentinel_tpu.runtime import (
    ENTRY_TYPE_IN, ENTRY_TYPE_OUT, Entry, Sentinel, pipeline_depth,
)
from sentinel_tpu.serving import (
    CadenceScheduler, DispatchPipeline, PipelinedVerdicts,
)
from sentinel_tpu.frontend import (
    AdaptiveBatcher, FrontendClosed, IngestOverload, RequestVerdict,
)

__version__ = "0.1.0"

__all__ = [
    "Sentinel", "Entry", "ENTRY_TYPE_IN", "ENTRY_TYPE_OUT",
    "FlowRule", "DegradeRule", "SystemRule", "AuthorityRule",
    "ParamFlowRule", "ParamFlowItem", "PARAM_BEHAVIOR_RATE_LIMITER",
    "BlockException", "FlowException", "DegradeException",
    "SystemBlockException", "AuthorityException", "ParamFlowException",
    "CustomSlotException", "BlockReason", "ErrorEntryFreeError",
    "HostGate", "DeviceSlot", "DeviceSlotView",
    "GRADE_QPS", "GRADE_THREAD", "GRADE_RT", "GRADE_EXCEPTION_RATIO",
    "GRADE_EXCEPTION_COUNT",
    "BEHAVIOR_DEFAULT", "BEHAVIOR_WARM_UP", "BEHAVIOR_RATE_LIMITER",
    "BEHAVIOR_WARM_UP_RATE_LIMITER",
    "STRATEGY_DIRECT", "STRATEGY_RELATE", "STRATEGY_CHAIN",
    "STRATEGY_WHITE", "STRATEGY_BLACK",
    "Clock", "ManualClock", "SystemClock", "set_global_clock",
    "ContextScope", "enter_context", "exit_context",
    "snapshot_context", "restore_context",
    "SentinelConfig", "load_config",
    "CadenceScheduler", "DispatchPipeline", "PipelinedVerdicts",
    "pipeline_depth",
    "AdaptiveBatcher", "RequestVerdict", "IngestOverload",
    "FrontendClosed",
]
