"""CI gate: the 2-process CPU mesh must decide exactly like 1 process.

Standalone (no pytest) so it can run as its own workflow job and fail
with a readable diff: launches the deterministic parity worker once per
topology — 1 process × 8 devices, then 2 coordinated processes × 4
devices — and compares every (status, wait_ms, remaining) triple.

Usage (from the repo root): python ci/multihost_smoke.py
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _parity(num_processes: int, devices_per_process: int) -> dict:
    from sentinel_tpu.multihost.launch import launch

    results = launch(["-m", "sentinel_tpu.multihost._parity_worker"],
                     num_processes,
                     devices_per_process=devices_per_process, timeout_s=300)
    for r in results:
        for line in r.stdout.splitlines():
            if line.startswith("PARITY_JSON:"):
                return json.loads(line.split(":", 1)[1])
    raise RuntimeError("parity worker produced no PARITY_JSON line")


def main() -> int:
    one = _parity(1, 8)
    two = _parity(2, 4)
    a, b = one["decisions"], two["decisions"]
    if a == b:
        statuses = sorted({d[0] for d in a})
        print(f"PARITY OK: {len(a)} decisions identical across topologies "
              f"(1x8dev vs 2x4dev); statuses seen: {statuses}")
        return 0
    print(f"PARITY FAILED: {len(a)} vs {len(b)} decisions", file=sys.stderr)
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            print(f"  first mismatch at {i}: 1proc={x} 2proc={y}",
                  file=sys.stderr)
            break
    return 1


if __name__ == "__main__":
    sys.exit(main())
