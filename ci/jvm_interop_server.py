"""JVM-interop harness server: a standalone token server for the CI job
that drives it with the REFERENCE Java client (Maven artifact
``com.alibaba.csp:sentinel-cluster-client-default`` — the real
``NettyTransportClient``/writer codec, not our golden frames).

Prints ``PORT <n>`` on stdout once listening, then serves until stdin
closes (the CI step runs it with a pipe and closes it when done).

Rule set: flow id 101, capacity 5/window — the Java side expects exactly
5 OK + 3 BLOCKED for an 8-request burst inside one second.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.parallel.cluster import (
    THRESHOLD_GLOBAL, ClusterEngine, ClusterFlowRule, ClusterSpec,
)


def main() -> None:
    eng = ClusterEngine(ClusterSpec(n_shards=1, flows_per_shard=16,
                                    namespaces=4))
    eng.load_rules("default", [ClusterFlowRule(
        flow_id=101, count=5.0, threshold_type=THRESHOLD_GLOBAL)])
    # warm the engine-step compile so the first RPC fits the reference
    # client's 20 ms request timeout budget is not blown by XLA compile
    eng.request_tokens([101], [1], now_ms=0)

    srv = ClusterTokenServer(eng, host="127.0.0.1", port=0)
    srv.start()

    # warm the REAL serving path (frame decode → batch step → reply) before
    # announcing the port: first-step XLA compiles would otherwise blow the
    # reference client's 20 ms request timeout while the server still
    # counts the grants. Unknown flow id → NO_RULE_EXISTS, no budget spent.
    from sentinel_tpu.cluster.client import ClusterTokenClient
    warm = ClusterTokenClient("127.0.0.1", srv.port,
                              request_timeout_ms=30_000)
    warm.start()
    for _ in range(3):
        warm.request_token(999, 1)
    warm.stop()

    print(f"PORT {srv.port}", flush=True)

    sys.stdin.read()       # serve until the driving step closes our stdin
    srv.stop()


if __name__ == "__main__":
    main()
