"""SPA end-to-end drive (playwright + chromium — the optional CI job).

No JS engine ships in the dev image, so locally the dashboard SPA is only
verified mechanically (tests/test_ui_contract.py). This script is the CI
counterpart that EXECUTES it: boot a real agent + dashboard (the
demos/dashboard_quickstart.py wiring), log in through the login form,
render every view (each ``viewX`` function runs), and round-trip one flow
rule through the editor modal. Console errors fail the run.

Usage (CI): ``pip install playwright && playwright install chromium``
then ``python ci/spa_e2e.py``. Exits non-zero on any failure.
"""

import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import sentinel_tpu as stpu
from sentinel_tpu.dashboard import Dashboard, DashboardServer
from sentinel_tpu.transport import start_transport

VIEWS = ["metrics", "resources", "tree", "machines", "cluster", "flow",
         "degrade", "paramFlow", "system", "authority", "gatewayFlow",
         "gatewayApi"]


def boot():
    """Agent + dashboard, ports ephemeral; returns (dash_port, stop)."""
    sph = stpu.Sentinel(stpu.load_config(
        app_name="spa-e2e", max_resources=64, max_flow_rules=16,
        max_degrade_rules=16, max_authority_rules=16))
    sph.load_flow_rules([stpu.FlowRule(resource="demo-res", count=100.0)])
    dash = DashboardServer(Dashboard(password="s3cr3t"), host="127.0.0.1",
                           port=0)
    dport = dash.start()
    from sentinel_tpu.gateway import (
        GatewayApiDefinitionManager, GatewayRuleManager,
    )
    gw = GatewayRuleManager(sph)
    apis = GatewayApiDefinitionManager()
    transport = start_transport(
        sph, host="0.0.0.0", port=0,
        dashboard_addr=f"127.0.0.1:{dport}", heartbeat_interval_ms=1000,
        gateway_manager=gw, api_definition_manager=apis)
    # embedded cluster coordinator: the dashboard's assign flow flips the
    # machine to SERVER mode and expects it to report its token-server
    # port (cluster/coordinator.py)
    from sentinel_tpu.cluster.coordinator import ClusterCoordinator
    coord = ClusterCoordinator(sph)
    coord.bind(transport.cluster_state)
    # traffic so metrics views have data
    for _ in range(20):
        try:
            with sph.entry("demo-res"):
                pass
        except stpu.BlockException:
            pass
    # authenticated poll (the discovery API requires a session)
    import http.cookiejar
    opener = urllib.request.build_opener(
        urllib.request.HTTPCookieProcessor(http.cookiejar.CookieJar()))
    login = urllib.request.Request(
        f"http://127.0.0.1:{dport}/auth/login", method="POST",
        data=json.dumps({"username": "sentinel",
                         "password": "s3cr3t"}).encode(),
        headers={"Content-Type": "application/json"})
    assert json.loads(opener.open(login, timeout=5).read())["success"]
    deadline = time.time() + 20
    while time.time() < deadline:        # wait for heartbeat discovery
        with opener.open(f"http://127.0.0.1:{dport}/app/names.json",
                         timeout=5) as r:
            if "spa-e2e" in (json.loads(r.read()).get("data") or []):
                break
        time.sleep(0.3)
    else:
        raise RuntimeError("agent never appeared in dashboard discovery")
    def traffic():
        """Fresh demo-res entries (the 1 s rolling window forgets the
        boot traffic long before later drive steps run)."""
        for _ in range(20):
            try:
                with sph.entry("demo-res"):
                    pass
            except stpu.BlockException:
                pass

    return dport, traffic, lambda: (transport.stop(), dash.stop())


def drive(dport: int, traffic) -> None:
    from playwright.sync_api import sync_playwright

    errors = []
    with sync_playwright() as pw:
        browser = pw.chromium.launch()
        page = browser.new_page()
        page.on("console", lambda m: errors.append(m.text)
                if m.type == "error" else None)
        page.on("pageerror", lambda e: errors.append(str(e)))

        page.goto(f"http://127.0.0.1:{dport}/", wait_until="networkidle")
        # ---- login form
        page.wait_for_selector("#login", state="visible", timeout=10000)
        page.fill("#u", "sentinel")
        page.fill("#p", "s3cr3t")
        page.click("#login button")
        page.wait_for_selector("#app", state="visible", timeout=10000)
        print("login OK")

        # ---- render every view (each viewX function executes)
        for view in VIEWS:
            page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/{view}")
            page.wait_for_timeout(700)
            assert page.locator("#content .card").count() >= 1, \
                f"view {view} rendered no card"
            print(f"view {view} OK")

        # ---- flow-rule editor round-trip: create via the modal, verify
        page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/flow")
        page.wait_for_timeout(700)
        page.click("text=+ new")
        page.wait_for_selector("#modal", timeout=5000)
        # field order follows SCHEMAS.flow: Resource is the first text input
        page.fill("#modal input >> nth=0", "e2e-res")
        page.fill("xpath=//div[@id='modal']//label[starts-with(normalize-"
                  "space(.), 'Threshold')]/following-sibling::input", "42")
        page.click("#modal button.primary")        # "Create"
        page.wait_for_selector("#modal", state="detached", timeout=5000)
        page.wait_for_timeout(700)
        assert page.locator("td", has_text="e2e-res").count() >= 1, \
            "saved rule not in table"
        print("flow rule editor round-trip OK")

        # ---- gateway flow editor round-trip
        page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/gatewayFlow")
        page.wait_for_timeout(700)
        page.click("text=+ new")
        page.wait_for_selector("#modal", timeout=5000)
        page.fill("#modal input >> nth=0", "e2e-route")
        page.click("#modal button.primary")
        page.wait_for_selector("#modal", state="detached", timeout=5000)
        page.wait_for_timeout(700)
        assert page.locator("td", has_text="e2e-route").count() >= 1, \
            "saved gateway rule not in table"
        print("gateway flow editor round-trip OK")

        # ---- gateway API definition editor round-trip
        page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/gatewayApi")
        page.wait_for_timeout(700)
        page.click("text=+ new")
        page.wait_for_selector("#modal", timeout=5000)
        page.fill("#modal input >> nth=0", "e2e-api-group")
        page.click("#modal button.primary")
        page.wait_for_selector("#modal", state="detached", timeout=5000)
        page.wait_for_timeout(700)
        assert page.locator("td", has_text="e2e-api-group").count() >= 1, \
            "saved API definition not in table"
        print("gateway API editor round-trip OK")

        # ---- node-tree view: root aggregate + resource rows + origin
        # drill-down (the reference webapp's identity page). Fresh
        # traffic first: jsonTree hides nodes idle over the rolling
        # second, and the boot traffic has long decayed by now.
        traffic()
        page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/tree")
        page.wait_for_timeout(700)
        assert page.locator("td", has_text="machine-root").count() >= 1, \
            "tree view missing the EntranceNode root row"
        assert page.locator("td", has_text="demo-res").count() >= 1, \
            "tree view missing the resource node"
        page.locator("tr", has_text="demo-res").locator(
            "text=origins").first.click()
        page.wait_for_timeout(700)
        assert page.locator(
            "text=no per-origin traffic").count() >= 1, \
            "origin drill-down did not open"
        print("node tree view OK")

        # ---- cluster assign flow: promote the machine to token server
        page.goto(f"http://127.0.0.1:{dport}/#/spa-e2e/cluster")
        page.wait_for_timeout(700)
        page.click("text=assign")
        page.wait_for_timeout(1500)
        assert page.locator("td", has_text="listening :").count() >= 1, \
            "assign did not promote the machine to a listening server"
        print("cluster assign OK")

        # ---- token-server config editor + QPS monitor appear once a
        # server exists (reference cluster_app_server_manage / _monitor)
        page.wait_for_timeout(1200)
        assert page.locator("h3", has_text="Token server config").count() == 1, \
            "server config card missing after assign"
        assert page.locator("h3", has_text="Token server QPS").count() == 1, \
            "QPS monitor card missing after assign"
        cfg_card = page.locator(".card", has_text="Token server config")
        cfg_card.locator("input[placeholder=unlimited]").fill("250")
        cfg_card.get_by_text("apply", exact=True).click()
        page.wait_for_timeout(700)
        assert cfg_card.locator("span", has_text="applied").count() >= 1, \
            "maxAllowedQps apply did not confirm"
        print("server config editor OK")
        browser.close()
    hard = [e for e in errors if "favicon" not in e]
    if hard:
        raise AssertionError(f"console errors: {hard}")


def main() -> int:
    dport, traffic, stop = boot()
    try:
        drive(dport, traffic)
    finally:
        stop()
    print("SPA E2E OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
