"""REAL Envoy binary interop (VERDICT r4 #5): an actual `envoy` process
configured with `rate_limit_service` pointing at `SentinelRlsGrpcServer`,
HTTP driven through its listener, OK/429 asserted per descriptor.

The dev image ships no Envoy binary and has no network egress, so this
harness cannot run there (`ci/envoy_golden.py` is the offline
wire-compat gate: canonical protoc-serialized frames replayed over real
gRPC). THIS script is the CI-side binary gate — the workflow downloads
the official static Envoy release and runs it for real.

Layout: [curl] → envoy :LPORT (http filter ratelimit, domain "prod",
generic_key action) → upstream :UPORT (python http server)
                      ↘ gRPC ShouldRateLimit → SentinelRlsGrpcServer :RPORT

Pass criteria: with descriptor ("generic_key","checkout") capped at 3/s,
a burst of 8 requests yields exactly 3x 200 then 429s
(failure_mode_deny=true, so a broken RLS path fails loudly as all-429
at request 1, and a bypassed filter fails as all-200).

Run: ENVOY_BIN=/path/to/envoy python ci/envoy_binary_interop.py
"""

from __future__ import annotations

import http.server
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


ENVOY_YAML = """\
admin:
  address: {{socket_address: {{address: 127.0.0.1, port_value: {aport}}}}}
static_resources:
  listeners:
  - address: {{socket_address: {{address: 127.0.0.1, port_value: {lport}}}}}
    filter_chains:
    - filters:
      - name: envoy.filters.network.http_connection_manager
        typed_config:
          "@type": type.googleapis.com/envoy.extensions.filters.network.http_connection_manager.v3.HttpConnectionManager
          stat_prefix: ingress
          http_filters:
          - name: envoy.filters.http.ratelimit
            typed_config:
              "@type": type.googleapis.com/envoy.extensions.filters.http.ratelimit.v3.RateLimit
              domain: prod
              failure_mode_deny: true
              transport_api_version: V3
              rate_limit_service:
                transport_api_version: V3
                grpc_service:
                  envoy_grpc: {{cluster_name: rls}}
          - name: envoy.filters.http.router
            typed_config:
              "@type": type.googleapis.com/envoy.extensions.filters.http.router.v3.Router
          route_config:
            name: rc
            virtual_hosts:
            - name: vh
              domains: ["*"]
              routes:
              - match: {{prefix: "/"}}
                route:
                  cluster: upstream
                  rate_limits:
                  - actions:
                    - generic_key: {{descriptor_value: checkout}}
  clusters:
  - name: upstream
    connect_timeout: 1s
    type: STATIC
    load_assignment:
      cluster_name: upstream
      endpoints:
      - lb_endpoints:
        - endpoint:
            address:
              socket_address: {{address: 127.0.0.1, port_value: {uport}}}
  - name: rls
    connect_timeout: 1s
    type: STATIC
    typed_extension_protocol_options:
      envoy.extensions.upstreams.http.v3.HttpProtocolOptions:
        "@type": type.googleapis.com/envoy.extensions.upstreams.http.v3.HttpProtocolOptions
        explicit_http_config: {{http2_protocol_options: {{}}}}
    load_assignment:
      cluster_name: rls
      endpoints:
      - lb_endpoints:
        - endpoint:
            address:
              socket_address: {{address: 127.0.0.1, port_value: {rport}}}
"""


def main() -> int:
    envoy = os.environ.get("ENVOY_BIN") or shutil.which("envoy")
    if not envoy:
        print("SKIP: no envoy binary (set ENVOY_BIN); the offline gate is "
              "ci/envoy_golden.py", file=sys.stderr)
        return 3

    # ---- upstream ----
    class Ok(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"upstream-ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    upstream = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Ok)
    uport = upstream.server_port
    threading.Thread(target=upstream.serve_forever, daemon=True).start()

    # ---- Sentinel RLS ----
    from sentinel_tpu.cluster.envoy_rls import (
        EnvoyRlsRule, EnvoyRlsRuleManager, EnvoyRlsService,
        RlsDescriptorRule, SentinelRlsGrpcServer,
    )
    from sentinel_tpu.parallel.cluster import ClusterEngine, ClusterSpec

    engine = ClusterEngine(ClusterSpec(n_shards=1, flows_per_shard=64,
                                       namespaces=4))
    mgr = EnvoyRlsRuleManager(engine)
    mgr.load_rules([EnvoyRlsRule(domain="prod", descriptors=[
        RlsDescriptorRule(entries=[("generic_key", "checkout")], count=3)])])
    service = EnvoyRlsService(engine, rules=mgr)
    rls = SentinelRlsGrpcServer(service, host="127.0.0.1", port=0)
    rport = rls.start()

    lport, aport = free_port(), free_port()
    cfg = ENVOY_YAML.format(lport=lport, uport=uport, rport=rport,
                            aport=aport)
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as fh:
        fh.write(cfg)
        cfg_path = fh.name

    proc = subprocess.Popen(
        [envoy, "-c", cfg_path, "--base-id", str(os.getpid() % 32000)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 30
        ready = False
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                raise RuntimeError(f"envoy exited rc={proc.returncode}:\n"
                                   f"{out[-4000:]}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{aport}/ready", timeout=1) as r:
                    if r.status == 200:
                        ready = True
                        break
            except Exception:
                time.sleep(0.3)
        if not ready:
            raise RuntimeError("envoy admin never became ready")

        codes = []
        for _ in range(10):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{lport}/", timeout=5) as r:
                    codes.append(r.status)
            except urllib.error.HTTPError as exc:
                codes.append(exc.code)
        print("codes:", codes)
        # cap=3/s over a burst of 10: the first 3 MUST pass and the tail
        # MUST be limited. The exact flip point may straddle one rolling-
        # window edge under a real clock (3 or 4 passes), so assert the
        # shape, not the point: monotone 200→429, ≥3 passes, ≥4 denials.
        assert codes[:3] == [200, 200, 200], codes
        assert codes[-4:] == [429, 429, 429, 429], codes
        flip = codes.index(429)
        assert all(c == 429 for c in codes[flip:]), codes
        print(f"ENVOY BINARY INTEROP OK: {flip}x200 then 429 via real "
              f"envoy -> SentinelRlsGrpcServer")
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        rls.stop()
        upstream.shutdown()
        os.unlink(cfg_path)


if __name__ == "__main__":
    raise SystemExit(main())
