"""Real-Envoy RLS wire interop via golden frames (VERDICT r3 #7).

No Envoy binary ships in this image, so interop is proven the way wire
compat is provable offline: the OFFICIAL protobuf toolchain (`protoc` +
the google.protobuf runtime) plays the Envoy client. This script

1. compiles `sentinel_tpu/cluster/proto/envoy_rls.proto` with the real
   `protoc` and serializes a canonical set of `ShouldRateLimit` requests
   with the official runtime — byte-for-byte what a real Envoy (which uses
   the same canonical proto3 serializer for these scalar/message fields)
   puts on the wire for those field values;
2. asserts those bytes EQUAL the golden frames committed in
   `tests/test_envoy_rls_golden.py` (drift in our trimmed descriptors
   would show up here);
3. replays them over a real gRPC channel against `SentinelRlsGrpcServer`
   and asserts OK/OVER_LIMIT parity per descriptor — including a frame
   carrying unknown fields (real Envoy sends fields our trimmed proto
   doesn't declare; proto3 skips them).

Run: python ci/envoy_golden.py   (CI job; also runnable locally)

The companion CI job `envoy-binary` goes further where a binary IS
available: `ci/envoy_binary_interop.py` downloads the official static
Envoy release, points its ratelimit http filter at
``SentinelRlsGrpcServer``, and asserts 200→429 through the real proxy.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tests.test_envoy_rls_golden import (  # noqa: E402
    GOLDEN_FRAMES, build_server, expected_codes,
)

PROTO = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "sentinel_tpu", "cluster", "proto",
    "envoy_rls.proto")


def official_pb2():
    """Compile the proto with the REAL protoc → generated module."""
    tmp = tempfile.mkdtemp(prefix="envoy-golden-")
    subprocess.run(
        ["protoc", f"--proto_path={os.path.dirname(PROTO)}",
         f"--python_out={tmp}", os.path.basename(PROTO)],
        check=True)
    # import under a distinct name so it does not collide with the
    # committed minimal descriptors in sentinel_tpu.cluster.proto
    spec = importlib.util.spec_from_file_location(
        "envoy_rls_official_pb2", os.path.join(tmp, "envoy_rls_pb2.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["envoy_rls_official_pb2"] = mod
    spec.loader.exec_module(mod)
    return mod


def main() -> None:
    pb = official_pb2()
    # re-serialize every golden frame's field values with the official
    # runtime and assert byte equality with the committed frames
    for name, (frame_hex, fields) in GOLDEN_FRAMES.items():
        req = pb.RateLimitRequest(domain=fields["domain"],
                                  hits_addend=fields.get("hits_addend", 0))
        for entries in fields["descriptors"]:
            d = req.descriptors.add()
            for k, v in entries:
                d.entries.add(key=k, value=v)
        got = req.SerializeToString().hex()
        want = frame_hex.replace("_unknown_suffix", "")
        if "_unknown_suffix" not in frame_hex:
            assert got == want, (
                f"{name}: official protoc serialization drifted from the "
                f"golden frame\n got={got}\nwant={want}")
        print(f"golden frame {name}: official-runtime bytes match")

    # replay over a real gRPC channel (the reference exercises its service
    # against generated stubs the same way —
    # SentinelEnvoyRlsServiceImplTest)
    import grpc

    server, port = build_server()
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        rpc = ch.unary_unary(
            "/envoy.service.ratelimit.v3.RateLimitService/ShouldRateLimit",
            request_serializer=lambda b: b,
            response_deserializer=pb.RateLimitResponse.FromString)
        for name, (frame_hex, fields) in GOLDEN_FRAMES.items():
            raw = bytes.fromhex(frame_hex.replace("_unknown_suffix", ""))
            if "_unknown_suffix" in frame_hex:
                # unknown field 15 (varint): proto3 must skip it
                raw += bytes([0x78, 0x2A])
            resp = rpc(raw)
            want_overall, want_codes = expected_codes(name)
            assert resp.overall_code == want_overall, (name, resp)
            got_codes = [s.code for s in resp.statuses]
            assert got_codes == want_codes, (name, got_codes, want_codes)
            print(f"golden frame {name}: OK/OVER_LIMIT parity "
                  f"({resp.overall_code}, {got_codes})")
    finally:
        server.stop()
    print("envoy golden interop: ALL PASS")


if __name__ == "__main__":
    main()
