package sentinel.tpu.interop;

import com.alibaba.csp.sentinel.cluster.ClusterConstants;
import com.alibaba.csp.sentinel.cluster.TokenResultStatus;
import com.alibaba.csp.sentinel.cluster.client.NettyTransportClient;
import com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfig;
import com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfigManager;
import com.alibaba.csp.sentinel.cluster.request.ClusterRequest;
import com.alibaba.csp.sentinel.cluster.request.data.FlowRequestData;
import com.alibaba.csp.sentinel.cluster.response.ClusterResponse;

/**
 * Drives the sentinel_tpu Python token server with the REFERENCE client:
 * real Netty framing, real writer codec, real PING handshake (the client
 * sends MSG_TYPE_PING on channelActive). Asserts OK/BLOCKED parity for an
 * 8-request burst against a flow rule with capacity 5.
 *
 * Usage: InteropCheck <host> <port>
 */
public final class InteropCheck {

    public static void main(String[] args) throws Exception {
        String host = args.length > 0 ? args[0] : "127.0.0.1";
        int port = Integer.parseInt(args.length > 1 ? args[1] : "18730");

        // generous timeout: a CI runner's first request may race residual
        // server-side warmup; correctness, not latency, is under test here
        ClusterClientConfigManager.applyNewConfig(
            new ClusterClientConfig().setRequestTimeout(5000));

        NettyTransportClient client = new NettyTransportClient(host, port);
        client.start();
        long deadline = System.currentTimeMillis() + 15000;
        while (!client.isReady() && System.currentTimeMillis() < deadline) {
            Thread.sleep(50);
        }
        if (!client.isReady()) {
            System.err.println("FAIL: client never became ready (PING handshake)");
            System.exit(2);
        }
        System.out.println("connected; PING handshake done");

        // align the burst to a fresh window second so the 5-token budget
        // can't straddle a rotation mid-burst
        long now = System.currentTimeMillis();
        Thread.sleep(1000 - (now % 1000) + 50);

        int ok = 0, blocked = 0, other = 0;
        for (int i = 0; i < 8; i++) {
            ClusterRequest<FlowRequestData> req = new ClusterRequest<>(
                ClusterConstants.MSG_TYPE_FLOW,
                new FlowRequestData().setFlowId(101).setCount(1).setPriority(false));
            ClusterResponse<?> resp = client.sendRequest(req);
            int status = resp.getStatus();
            if (status == TokenResultStatus.OK) {
                ok++;
            } else if (status == TokenResultStatus.BLOCKED) {
                blocked++;
            } else {
                other++;
                System.err.println("unexpected status: " + status);
            }
        }
        client.stop();
        System.out.println("results: OK=" + ok + " BLOCKED=" + blocked
                + " other=" + other);
        if (ok == 5 && blocked == 3 && other == 0) {
            System.out.println("JVM INTEROP OK");
            System.exit(0);
        }
        System.err.println("FAIL: expected OK=5 BLOCKED=3");
        System.exit(1);
    }

    private InteropCheck() {
    }
}
